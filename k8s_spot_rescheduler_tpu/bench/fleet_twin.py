"""The fleet twin: hundreds of tenant twins vs a real replica set on
one virtual clock — the harness that measures what no per-tick test
can.

The service era gives one TPU a fleet of tenants, but its proofs run
four agents. This module drives the design point: a heterogeneous
fleet of :class:`service.twin.TenantTwin` agents (mixed cluster-size
tiers, per-twin cadences and churn rates, zone-correlated spot storms,
tenants joining and leaving mid-run) against >= 2 real-HTTP
``ServiceServer`` replicas that share one ``FakeClock``. Wall time
stays in minutes because the device is MODELED: each replica's
``solve_hook`` advances the virtual clock by a per-batch cost
(base + per-lane) before running the numpy-oracle solve, so tenant
queue waits accrue in SIMULATED seconds and saturation emerges from
the same DRR queue / bucket batching / admission edges production
runs — while every served selection stays bit-identical to a solo
in-process plan (spot-checked continuously, serve-smoke's contract at
fleet scale).

Outputs (one JSON artifact line via ``bench.py --fleet-twin``):

- the **capacity-planning curve**: per load phase, device occupancy vs
  queue-wait p50/p99, and the derived tenants-per-device at the
  declared queue-wait SLO;
- **failover convexity**: a replica is killed (graceful) and restarted
  inside every phase; the p99 degradation during the kill window, per
  load level, measures how much headroom failover actually needs;
- **fairness**: Jain's index over per-twin served/offered shares;
- **compile sharing**: bucket-level first-compile hits/misses as twin
  shapes drift (storms change packed shapes mid-run);
- **admission-shed ledger**: every shed edge double-booked — the
  labeled metric vs the flight ``service-shed``/``resync-shed`` events
  — asserted equal, plus a deterministic per-reason edge-induction
  pass (:func:`induce_shed_edges`) that fires every reason in the
  REGISTRY's label set at least once and diffs both surfaces per
  label;
- **restart-storm survival**: after the ramped phases, one replica is
  killed and warm-restarted under the full fleet (tenant cache wiped);
  the run asserts bounded concurrent full-pack ingests, no tenant
  resyncing twice, server-vs-twin resync ledger parity, unaffected
  tenants holding the SLO, and convergence in O(affected) full packs.

``bench.py --fleet-twin-smoke`` runs the same loop at <= 64 twins
inside ``make check``; the full run (512 twins, one simulated hour)
is ``--fleet-twin``.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np

from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS
from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.columnar import pack_fingerprint
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.service.server import ServiceServer
from k8s_spot_rescheduler_tpu.service.twin import (
    TenantTwin,
    fleet_specs,
    post_plan,
)
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log

# the shed-reason label set comes from the REGISTRY, not a local
# literal: a new admission edge added to the service shows up here
# automatically, and ``induce_shed_edges`` then FAILS until it also has
# a deterministic recipe for firing it — the completeness contract
SHED_REASONS = metrics.SHED_REASONS

# the two flight kinds an admission shed can fire as (the resync-storm
# edge has its own kind so storm ingest refusals are separable from
# ordinary queue sheds in the flight log); every ledger diff in this
# module must sum BOTH to stay equal to the labeled metric
SHED_FLIGHT_KINDS = ("service-shed", "resync-shed")


def _shed_flight_total() -> int:
    counts = flight.counts()
    return sum(int(counts.get(k, 0)) for k in SHED_FLIGHT_KINDS)


def _pctl(values: List[float], q: float) -> float:
    """Nearest-rank percentile (same convention as the registry's
    windowed gauges, so the bench's curve and /healthz agree on what
    'p99' means)."""
    if not values:
        return 0.0
    ranked = sorted(values)
    import math

    idx = min(len(ranked) - 1, max(0, int(math.ceil(q * len(ranked))) - 1))
    return float(ranked[idx])


def _shed_totals() -> Dict[str, int]:
    return {
        k: int(v)
        for k, v in metrics.service_snapshot().get(
            "admission_shed", {}
        ).items()
    }


def _shed_delta(before: Dict[str, int]) -> Dict[str, int]:
    out = {}
    for reason, v in _shed_totals().items():
        d = v - before.get(reason, 0)
        if d:
            out[reason] = d
    return out


class _Fleet:
    """The replica set + bookkeeping one fleet run owns."""

    def __init__(self, cfg: ReschedulerConfig, clock: FakeClock,
                 n_replicas: int, max_inflight: int,
                 cost_base_s: float, cost_per_lane_s: float,
                 calibration: Optional[Dict[str, dict]] = None):
        self.cfg = cfg
        self.clock = clock
        self.max_inflight = max_inflight
        self.cost_base_s = cost_base_s
        self.cost_per_lane_s = cost_per_lane_s
        # measured per-bucket solve costs (bucket key -> {"solve_s"}),
        # from a real --carry-wall run's ``twin_calibration`` table:
        # when a batch's bucket has a measured cost, the modeled device
        # charges THAT instead of the synthetic base+per-lane line
        self.calibration: Dict[str, dict] = dict(calibration or {})
        self.busy_s = [0.0] * n_replicas  # modeled device time, per slot
        # per-replica device frontier: the virtual time through which
        # that replica's modeled TPU is committed. Parallel replicas
        # must OVERLAP in virtual time (naively advancing the shared
        # clock by every batch cost would serialize the fleet's devices
        # and cap occupancy at 1/n); a batch starts at
        # max(its replica's frontier, its own last-enqueue time) and
        # the global clock only catches UP to frontiers, so each
        # device serializes its own batches while devices run
        # concurrently.
        self.frontier = [0.0] * n_replicas
        self._adv_lock = threading.Lock()
        self.replicas: List[Optional[ServiceServer]] = [None] * n_replicas
        self.addrs: List[str] = []
        for i in range(n_replicas):
            self.replicas[i] = self._spawn(i, "127.0.0.1:0")
            self.addrs.append(self.replicas[i].address)

    def _spawn(self, idx: int, addr: str) -> ServiceServer:
        srv = ServiceServer(
            self.cfg, addr, batch_window_s=0.0,
            max_inflight=self.max_inflight, clock=self.clock,
        )
        svc = srv.service
        clock = self.clock
        busy = self.busy_s

        def hook(stacked, batch):
            # the modeled TPU: virtual device time per batch, committed
            # against THIS replica's frontier so queue waits accrue in
            # simulated seconds while the numpy oracle keeps answers
            # bit-exact. The batch could not have started before its
            # last member enqueued — that lower bound (not clock.now(),
            # which a concurrent replica may already have advanced)
            # keeps parallel devices overlapped in virtual time.
            measured = (
                self.calibration.get(batch[0].bucket.key)
                if batch else None
            )
            if measured is not None:
                cost = float(measured.get("solve_s", 0.0)) or (
                    self.cost_base_s
                )
            else:
                # the device solves every tenant's FULL lane block no
                # matter how few lanes a delta request touched: charge
                # the stacked batch's valid candidate lanes (equal to
                # the DRR cost for full packs), not r.lanes, which for
                # delta traffic counts only the CHANGED lanes and would
                # make deltas read as nearly free device time
                lanes = (
                    int(np.asarray(stacked.cand_valid).sum())
                    if stacked is not None
                    else sum(r.lanes for r in batch)
                )
                cost = (
                    self.cost_base_s + self.cost_per_lane_s * lanes
                )
            ready = max((r.enqueued for r in batch), default=0.0)
            with self._adv_lock:
                start = max(self.frontier[idx], ready)
                end = start + cost
                self.frontier[idx] = end
                behind = end - clock.now()
                if behind > 0:
                    clock.advance(behind)
            busy[idx] += cost
            return svc._solve(stacked)

        svc.solve_hook = hook
        srv.start_background(scheduler=True)
        return srv

    def kill(self, idx: int) -> None:
        srv = self.replicas[idx]
        if srv is not None:
            srv.graceful_shutdown()
            self.replicas[idx] = None

    def restart(self, idx: int) -> None:
        if self.replicas[idx] is None:
            self.replicas[idx] = self._spawn(idx, self.addrs[idx])

    def close(self) -> None:
        for i, srv in enumerate(self.replicas):
            if srv is not None:
                srv.graceful_shutdown()
                self.replicas[i] = None


def fleet_twin(
    n_twins: int = 512,
    n_replicas: int = 2,
    sim_s: float = 3600.0,
    seed: int = 0,
    slo_ms: float = 750.0,
    phases: int = 4,
    zones: int = 4,
    cost_base_s: float = 0.25,
    cost_per_lane_s: float = 0.004,
    storm_frac: float = 0.5,
    storm_len_s: float = 90.0,
    leave_frac: float = 0.05,
    max_inflight: int = 16,
    pool_workers: int = 32,
    verify_every: int = 7,
    jain_min: float = 0.8,
    max_wall_s: float = 280.0,
    deadline_frac: float = 0.0,
    resync_storm_s: float = 240.0,
    calibration: Optional[Dict[str, dict]] = None,
) -> dict:
    """Run the fleet twin; returns the capacity/observability artifact
    (``ok`` False plus a ``failures`` list when any fleet invariant
    broke). See the module docstring for what each phase does.

    After the ramped phases, ``resync_storm_s`` > 0 appends a dedicated
    **restart-storm** phase under the full fleet: one replica is killed
    and warm-restarted (its tenant cache wiped), and the run asserts
    the anti-entropy contract — bounded concurrent full-pack ingests
    (``resync_ingest_inflight_max`` <= the configured cap), no tenant
    resyncing twice, server resync count == the twins' sum, unaffected
    tenants holding the queue-wait SLO, and convergence in O(affected)
    full packs. ``calibration`` maps bucket keys to measured per-batch
    solve costs (see ``--twin-calibration``)."""
    t_wall = time.perf_counter()
    clock = FakeClock()
    spec0 = CONFIGS[2]
    cfg = ReschedulerConfig(
        resources=spec0.resources, solver="numpy",
        device_sick_threshold=0, service_drain_grace=2.0,
        planner_timeout=5.0,
        # short drain schedules keep the twins' periodic wire-v3
        # requests (every SCHEDULE_EVERY-th tick) cheap enough for the
        # modeled device while still exercising the surface at scale
        schedule_horizon=6,
    )
    fleet = _Fleet(cfg, clock, n_replicas, max_inflight,
                   cost_base_s, cost_per_lane_s,
                   calibration=calibration)
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner

    solo = SolverPlanner(cfg)
    specs = fleet_specs(n_twins, seed=seed, zones=zones,
                        deadline_frac=deadline_frac)
    rng = np.random.default_rng(seed ^ 0xF1EE7)
    twins: Dict[int, TenantTwin] = {}   # spec index -> twin (ever built)
    active: List[int] = []
    ever_active: set = set()
    next_fresh = 0                      # first never-activated spec index

    def activate(i: int) -> None:
        if i not in twins:
            order = [
                fleet.addrs[(i + k) % n_replicas]
                for k in range(n_replicas)
            ]
            twins[i] = TenantTwin(
                specs[i], cfg, clock,
                [f"http://{a}" for a in order],
            )
        tw = twins[i]
        tw.next_due = clock.now() + float(
            rng.uniform(0, tw.spec.cadence_s)
        )
        active.append(i)
        ever_active.add(i)

    mismatches: List[dict] = []
    verified = 0
    failures: List[str] = []
    curve: List[dict] = []
    fo_rows: List[dict] = []
    storm_window_hits: List[int] = []
    resync_before = metrics.service_snapshot()["delta_requests"].get(
        "resync", 0
    )
    shed_metric_0 = sum(_shed_totals().values())
    shed_flight_0 = _shed_flight_total()
    fo_metric_0 = metrics.service_snapshot()["remote_planner_failover"]
    fo_flight_0 = flight.counts().get("failover", 0)

    pool = ThreadPoolExecutor(max_workers=pool_workers)
    phase_len = sim_s / phases
    aborted = ""
    try:
        for p in range(phases):
            phase_start = clock.now()
            phase_end = phase_start + phase_len
            target = int(np.ceil(n_twins * (p + 1) / phases))
            # tenant leave/join churn at the boundary: a slice of the
            # active set departs, replaced (plus the ramp) by fresh
            # twins — the service's bucket map must churn without any
            # delta-wire resync storm (asserted at the end)
            if p > 0 and leave_frac > 0 and active:
                n_leave = max(1, int(len(active) * leave_frac))
                for i in list(rng.choice(active, size=n_leave,
                                         replace=False)):
                    active.remove(int(i))
            while len(active) < target:
                if next_fresh < n_twins:
                    i, next_fresh = next_fresh, next_fresh + 1
                else:  # pool exhausted: rejoin a departed tenant
                    candidates = [
                        j for j in range(n_twins) if j not in active
                    ]
                    if not candidates:
                        break
                    i = int(rng.choice(candidates))
                if i not in active:
                    activate(i)
            metrics.reset_service_window()
            busy_mark = sum(fleet.busy_s)
            marks = {i: len(twins[i].wait_samples_ms) for i in active}
            served_mark = {i: twins[i].served for i in active}
            offered_mark = {i: twins[i].offered for i in active}
            shed_mark = _shed_totals()
            # disjoint scenario windows inside each phase: the storm
            # burst settles before the replica kill, so the failover
            # degradation is measured against steady state, not against
            # (or inside) the storm's own tail
            storm_at = phase_start + 0.45 * phase_len
            storm_restore_at = storm_at + min(
                storm_len_s, 0.15 * phase_len
            )
            fo_start = phase_start + 0.70 * phase_len
            fo_end = phase_start + 0.80 * phase_len
            kill_idx = p % n_replicas
            storm_zone = p % zones
            stormed: List[int] = []
            # actual fire times of the scenario windows: waits are
            # classified by request ENQUEUE time against these, so a
            # request queued during the outage counts against the
            # outage even when it is only served after the restart
            win: Dict[str, float] = {}
            fired = set()

            def fire_events(now: float) -> None:
                if "storm" not in fired and now >= storm_at:
                    fired.add("storm")
                    win["s0"] = now
                    hits = 0
                    for i in active:
                        tw = twins[i]
                        if tw.spec.zone != storm_zone:
                            continue
                        if tw.spot_interrupt(storm_frac):
                            hits += 1
                            stormed.append(i)
                            # interrupted capacity demands an immediate
                            # replan — the correlated burst the storm
                            # exists to model
                            tw.next_due = now + float(rng.uniform(0, 5))
                    storm_window_hits.append(hits)
                if "restore" not in fired and now >= storm_restore_at:
                    fired.add("restore")
                    win["s1"] = now
                    for i in stormed:
                        twins[i].spot_restore()
                if "kill" not in fired and now >= fo_start:
                    fired.add("kill")
                    win["f0"] = now
                    win["busy0"] = sum(
                        b for j, b in enumerate(fleet.busy_s)
                        if j != kill_idx
                    )
                    fleet.kill(kill_idx)
                if "restart" not in fired and now >= fo_end:
                    fired.add("restart")
                    win["f1"] = now
                    win["busy1"] = sum(
                        b for j, b in enumerate(fleet.busy_s)
                        if j != kill_idx
                    )
                    fleet.restart(kill_idx)

            def next_event_time() -> float:
                times = [phase_end]
                if "storm" not in fired:
                    times.append(storm_at)
                if "restore" not in fired:
                    times.append(storm_restore_at)
                if "kill" not in fired:
                    times.append(fo_start)
                if "restart" not in fired:
                    times.append(fo_end)
                return min(times)

            while clock.now() < phase_end:
                if time.perf_counter() - t_wall > max_wall_s:
                    aborted = (
                        "wall budget %.0fs exhausted in phase %d"
                        % (max_wall_s, p)
                    )
                    break
                now = clock.now()
                fire_events(now)
                due = [i for i in active if twins[i].next_due <= now]
                if not due:
                    nxt = min(
                        min(twins[i].next_due for i in active),
                        next_event_time(),
                    )
                    clock.advance(max(1e-3, nxt - now))
                    continue
                list(pool.map(lambda i: twins[i].tick(), due))
                for i in due:
                    tw = twins[i]
                    # bit-identity spot checks: every twin's first
                    # served tick, then a steady sample — BEFORE churn
                    # mutates the store the served plan was packed from
                    if tw.last_reply is not None and (
                        tw.served == 1 or tw.served % verify_every == 0
                    ):
                        bad = tw.verify(solo)
                        verified += 1
                        if bad is not None:
                            mismatches.append(bad)
                    # jittered cadence: a joint dispatch round must not
                    # phase-lock its cohort (identical next_due would
                    # turn every later round into one synchronized
                    # burst whose queue waits read as saturation at any
                    # load). A pending resync retry (retry_due > 0)
                    # overrides the cadence: the twin owes the server
                    # exactly one full pack, on ITS jittered schedule
                    if tw.retry_due > 0:
                        tw.next_due = tw.retry_due
                    else:
                        tw.next_due = clock.now() + tw.spec.cadence_s * (
                            float(tw.rng.uniform(0.7, 1.3))
                        )
                    tw.churn()
            if aborted:
                break
            # make sure phase events all fired even if the tick stream
            # went quiet near the boundary
            fire_events(clock.now())

            dur = max(1e-9, clock.now() - phase_start)
            occupancy = (sum(fleet.busy_s) - busy_mark) / (
                dur * n_replicas
            )
            healthy: List[float] = []
            storm_tail: List[float] = []
            failover: List[float] = []
            inf = float("inf")
            s0, s1 = win.get("s0", inf), win.get("s1", inf)
            f0, f1 = win.get("f0", inf), win.get("f1", inf)
            for i in active:
                tw = twins[i]
                a = marks.get(i, 0)
                # steady state excludes both scenario windows, so the
                # capacity curve and the failover baseline are not
                # polluted by the storm's own burst
                for t, w in zip(
                    tw.wait_sample_t[a:], tw.wait_samples_ms[a:]
                ):
                    if s0 <= t < s1:
                        storm_tail.append(w)
                    elif f0 <= t < f1:
                        failover.append(w)
                    else:
                        healthy.append(w)
            shares = [
                (twins[i].served - served_mark.get(i, 0))
                / max(1, twins[i].offered - offered_mark.get(i, 0))
                for i in active
                if twins[i].offered > offered_mark.get(i, 0)
            ]
            row = {
                "phase": p,
                "active_twins": len(active),
                "tenants_per_device": round(len(active) / n_replicas, 2),
                "occupancy": round(occupancy, 4),
                "queue_wait_p50_ms": round(_pctl(healthy, 0.50), 3),
                "queue_wait_p99_ms": round(_pctl(healthy, 0.99), 3),
                "queue_wait_p99_storm_ms": round(
                    _pctl(storm_tail, 0.99), 3
                ),
                "served": sum(
                    twins[i].served - served_mark.get(i, 0)
                    for i in active
                ),
                "jain": round(metrics.jain_fairness(shares), 4),
                "storm_hits": storm_window_hits[-1]
                if storm_window_hits else 0,
                "sheds": _shed_delta(shed_mark),
            }
            curve.append(row)
            mean_h = sum(healthy) / len(healthy) if healthy else 0.0
            mean_f = sum(failover) / len(failover) if failover else 0.0
            fo_dur = max(1e-9, win.get("f1", clock.now())
                         - win.get("f0", clock.now()))
            survivors = max(1, n_replicas - 1)
            surv_occ = (
                win.get("busy1", 0.0) - win.get("busy0", 0.0)
            ) / (fo_dur * survivors)
            fo_rows.append({
                "active_twins": len(active),
                "p99_healthy_ms": row["queue_wait_p99_ms"],
                "p99_failover_ms": round(_pctl(failover, 0.99), 3),
                "mean_healthy_ms": round(mean_h, 3),
                "mean_failover_ms": round(mean_f, 3),
                "degradation_ms": round(mean_f - mean_h, 3),
                # the robust convexity signal: how hot the surviving
                # replica(s) ran while one was down. Below saturation
                # the fleet absorbs a replica loss by consolidating
                # into bigger shared batches (waits can even DROP);
                # the loss of headroom shows up here first, and wait
                # degradation only goes positive once the survivor
                # pins at ~1.0
                "survivor_occupancy": round(surv_occ, 4),
                "failover_samples": len(failover),
            })
            log.info(
                "fleet-twin phase %d: active=%d occ=%.2f p99=%.0fms "
                "jain=%.3f sheds=%s",
                p, len(active), occupancy, row["queue_wait_p99_ms"],
                row["jain"], row["sheds"],
            )

        # --------------------------------------------------------------
        # dedicated restart storm: kill + warm-restart ONE replica under
        # the full fleet, wiping its tenant cache. Every active tenant
        # whose primary it is owes one full-pack resync, all at once —
        # the admission class must SHED the excess (bounded concurrent
        # ingests), never collapse (unaffected tenants hold the SLO),
        # and the fleet must converge in O(affected) full packs with no
        # tenant resyncing twice.
        storm_report: dict = {}
        if not aborted and resync_storm_s > 0 and active:
            storm_kill = phases % n_replicas  # rotate past the phase kills
            metrics.reset_service_window()  # arm the ingest high-water
            sm_resync_0 = metrics.service_snapshot()[
                "delta_requests"
            ].get("resync", 0)
            sm_shed_0 = _shed_totals().get("resync-storm", 0)
            sm_shed_flight_0 = flight.counts().get("resync-shed", 0)
            tw_resync_0 = {i: twins[i].resyncs for i in active}
            tw_fulls_0 = sum(twins[i].full_posts for i in active)
            tw_sched_0 = sum(twins[i].schedule_ticks for i in active)
            tw_bytes_0 = sum(twins[i].wire_bytes_sent for i in active)
            wait_mark = {i: len(twins[i].wait_samples_ms) for i in active}
            affected = [
                i for i in active if i % n_replicas == storm_kill
            ]
            storm_t0 = clock.now()
            fleet.kill(storm_kill)
            fleet.restart(storm_kill)  # warm restart: cache wiped
            srv_restarted = fleet.replicas[storm_kill]
            affected_set = set(affected)
            # the correlated wave: every AFFECTED twin re-ticks within
            # seconds of the restart (their cadences all land on the
            # fresh cache together — the storm this phase exists for);
            # unaffected twins keep their natural cadence, pulled into
            # the window only so their SLO has samples to judge
            for i in active:
                tw = twins[i]
                tw.retry_due = 0.0
                if i in affected_set:
                    tw.next_due = storm_t0 + float(
                        rng.uniform(0.0, 10.0)
                    )
                else:
                    tw.next_due = min(
                        tw.next_due,
                        storm_t0 + float(rng.uniform(0.3, 1.0)) * min(
                            tw.spec.cadence_s, resync_storm_s * 0.5
                        ),
                    )
            storm_end = storm_t0 + resync_storm_s
            # the isolation bound for unaffected tenants: the storm
            # must not make them materially worse than the load the
            # ramp ALREADY exhibited (the top phases may sit past the
            # SLO knee by design — that saturation is the capacity
            # curve's finding, not the storm's fault). Baseline = the
            # worst steady-state p99 of any ramp phase. The affected
            # cohort can be half the fleet, so DRR fair-share alone
            # puts 2x that load on the unaffected while the herd
            # re-seeds, and past the knee queue waits grow
            # superlinearly — 3x the baseline is the survival band
            # (512-twin measured: 2.3x); COLLAPSE, the thing the
            # admission class exists to prevent, reads as an order of
            # magnitude, not a fair-share doubling.
            pre_storm_p99 = max(
                (r["queue_wait_p99_ms"] for r in curve), default=0.0
            )
            storm_slo = max(slo_ms, 3.0 * pre_storm_p99)
            converge_ticks = 0
            converged_s = 0.0

            def _storm_converged() -> bool:
                # ground truth of anti-entropy: the wiped cache holds
                # every affected (primary-owner) tenant again, and no
                # twin still owes a full pack
                svc = srv_restarted.service
                return all(
                    svc.tenant_cached(twins[i].spec.name)
                    for i in affected
                ) and not any(twins[i]._need_full for i in active)

            while clock.now() < storm_end:
                if time.perf_counter() - t_wall > max_wall_s:
                    aborted = (
                        "wall budget %.0fs exhausted in restart storm"
                        % max_wall_s
                    )
                    break
                if converged_s == 0.0 and _storm_converged():
                    converged_s = clock.now() - storm_t0
                    break
                now = clock.now()
                due = [i for i in active if twins[i].next_due <= now]
                if not due:
                    nxt = min(
                        min(twins[i].next_due for i in active), storm_end
                    )
                    clock.advance(max(1e-3, nxt - now))
                    continue
                converge_ticks += 1
                list(pool.map(lambda i: twins[i].tick(), due))
                for i in due:
                    tw = twins[i]
                    if tw.last_reply is not None and (
                        tw.served == 1 or tw.served % verify_every == 0
                    ):
                        bad = tw.verify(solo)
                        verified += 1
                        if bad is not None:
                            mismatches.append(bad)
                    if tw.retry_due > 0:
                        tw.next_due = tw.retry_due
                    else:
                        # an affected twin still owing anti-entropy
                        # (primary cache not yet re-seeded) re-ticks
                        # within a minute so convergence completes in
                        # the window; everyone else keeps their natural
                        # cadence. No churn in this phase — the
                        # full-pack ledger below then counts ONLY
                        # resync traffic (plus scheduled v3 fulls),
                        # not shape growth
                        cad = tw.spec.cadence_s
                        if i in affected_set and not (
                            srv_restarted.service.tenant_cached(
                                tw.spec.name
                            )
                        ):
                            cad = min(cad, 60.0)
                        tw.next_due = clock.now() + cad * float(
                            tw.rng.uniform(0.7, 1.3)
                        )
            if converged_s == 0.0 and _storm_converged():
                converged_s = clock.now() - storm_t0

            sm_resync = metrics.service_snapshot()[
                "delta_requests"
            ].get("resync", 0) - sm_resync_0
            sm_shed = _shed_totals().get("resync-storm", 0) - sm_shed_0
            sm_shed_flight = (
                flight.counts().get("resync-shed", 0) - sm_shed_flight_0
            )
            tw_resync = {
                i: twins[i].resyncs - tw_resync_0[i] for i in active
            }
            storm_fulls = (
                sum(twins[i].full_posts for i in active) - tw_fulls_0
                - (sum(twins[i].schedule_ticks for i in active)
                   - tw_sched_0)
            )
            unaffected_waits = [
                w
                for i in active if i not in affected_set
                for w in twins[i].wait_samples_ms[wait_mark.get(i, 0):]
            ]
            storm_p99 = _pctl(unaffected_waits, 0.99)
            ingest_max = metrics.service_snapshot().get(
                "resync_ingest_inflight_max", 0
            )
            cap = int(cfg.service_resync_ingest_cap)
            if converged_s == 0.0 and not aborted:
                failures.append(
                    "restart storm did not converge within %.0fs: "
                    "%d/%d affected tenants re-cached"
                    % (
                        resync_storm_s,
                        sum(
                            1 for i in affected
                            if srv_restarted.service.tenant_cached(
                                twins[i].spec.name
                            )
                        ),
                        len(affected),
                    )
                )
            if ingest_max > cap:
                failures.append(
                    f"concurrent resync ingests peaked at {ingest_max} "
                    f"> cap {cap}"
                )
            twice = {
                twins[i].spec.name: n
                for i, n in tw_resync.items() if n > 1
            }
            if twice:
                failures.append(
                    f"tenants resynced more than once in one storm: "
                    f"{twice}"
                )
            if sm_resync != sum(tw_resync.values()):
                failures.append(
                    f"storm resync ledgers disagree: server {sm_resync} "
                    f"!= twins {sum(tw_resync.values())}"
                )
            if sm_shed != sm_shed_flight:
                failures.append(
                    f"resync-shed ledgers disagree: metric {sm_shed} "
                    f"!= flight {sm_shed_flight}"
                )
            if storm_fulls > 2 * len(affected) + len(active):
                failures.append(
                    f"storm full-pack traffic not O(tenants): "
                    f"{storm_fulls} fulls for {len(affected)} affected "
                    f"/ {len(active)} active"
                )
            if storm_p99 > storm_slo:
                failures.append(
                    f"unaffected tenants broke the SLO during the "
                    f"storm: p99 {storm_p99:.0f}ms > {storm_slo:.0f}ms "
                    f"(slo {slo_ms}ms, pre-storm p99 "
                    f"{pre_storm_p99:.0f}ms)"
                )
            storm_report = {
                "affected": len(affected),
                "active": len(active),
                "resyncs_server": sm_resync,
                "resyncs_twins": sum(tw_resync.values()),
                "resync_sheds": sm_shed,
                "resync_sheds_flight": sm_shed_flight,
                "ingest_inflight_max": int(ingest_max),
                "ingest_cap": cap,
                "full_packs": storm_fulls,
                "wire_bytes": sum(
                    twins[i].wire_bytes_sent for i in active
                ) - tw_bytes_0,
                "converge_ticks": converge_ticks,
                "converge_s": round(converged_s, 1),
                "p99_unaffected_ms": round(storm_p99, 3),
                "storm_slo_ms": round(storm_slo, 1),
            }
            log.info(
                "fleet-twin restart storm: affected=%d resyncs=%d "
                "sheds=%d ingest_max=%d/%d converged in %d ticks "
                "(%.0fs sim) p99=%.0fms",
                len(affected), sm_resync, sm_shed, ingest_max, cap,
                converge_ticks, converged_s, storm_p99,
            )
            if aborted:
                failures.append(aborted)
                aborted = ""
    finally:
        pool.shutdown(wait=True)
        fleet.close()

    # ------------------------------------------------------------------
    # fleet invariants

    crashes = sum(tw.crashes for tw in twins.values())
    if aborted:
        failures.append(aborted)
    if crashes:
        failures.append(f"{crashes} twin crash(es)")
    if mismatches:
        failures.append(
            f"{len(mismatches)} selection mismatch(es) vs solo plans"
        )
    if len(ever_active) < min(n_twins, len(specs)):
        failures.append(
            f"only {len(ever_active)}/{n_twins} twins ever activated"
        )
    occ = [r["occupancy"] for r in curve]
    p99s = [r["queue_wait_p99_ms"] for r in curve]
    if len(curve) < phases:
        failures.append(f"only {len(curve)}/{phases} curve points")
    if any(b <= a for a, b in zip(occ, occ[1:])):
        failures.append(f"occupancy curve not increasing: {occ}")
    if curve and not p99s[-1] > p99s[0]:
        failures.append(
            f"degenerate queue-wait curve: p99 {p99s}"
        )
    if curve and p99s[0] > slo_ms:
        failures.append(
            f"lightest phase already violates the {slo_ms}ms SLO"
        )
    capacity = 0.0
    for r in curve:
        if r["queue_wait_p99_ms"] <= slo_ms:
            capacity = max(capacity, r["tenants_per_device"])
    all_shares = [
        tw.served / tw.offered
        for tw in twins.values() if tw.offered
    ]
    jain_fleet = metrics.jain_fairness(all_shares)
    if jain_fleet < jain_min:
        failures.append(
            f"fleet Jain {jain_fleet:.3f} < {jain_min}"
        )
    # double-booked degradation ledgers: cumulative flight event counts
    # vs the metric counters must agree exactly (shed + failover edges)
    shed_metric = sum(_shed_totals().values()) - shed_metric_0
    shed_flight = _shed_flight_total() - shed_flight_0
    if shed_metric != shed_flight:
        failures.append(
            f"shed ledgers disagree: metric {shed_metric} != "
            f"flight {shed_flight}"
        )
    fo_metric = (
        metrics.service_snapshot()["remote_planner_failover"] - fo_metric_0
    )
    fo_flight = flight.counts().get("failover", 0) - fo_flight_0
    if fo_metric != fo_flight:
        failures.append(
            f"failover ledgers disagree: metric {fo_metric} != "
            f"flight {fo_flight}"
        )
    if fo_metric <= 0:
        failures.append("no failover edges induced by the kill windows")
    # resync PARITY, not resync zero: phase kills and the restart storm
    # legitimately stale the delta bases, so resyncs happen — what must
    # hold is that every server-side resync demand is one twin's
    # observed demand (no lost or phantom anti-entropy), and that no
    # twin resyncs more than once per restart event
    resyncs = (
        metrics.service_snapshot()["delta_requests"].get("resync", 0)
        - resync_before
    )
    twin_resyncs = sum(tw.resyncs for tw in twins.values())
    if resyncs != twin_resyncs:
        failures.append(
            f"resync ledgers disagree: server {resyncs} != "
            f"twins {twin_resyncs}"
        )
    restarts_total = phases + (1 if resync_storm_s > 0 else 0)
    worst = max((tw.resyncs for tw in twins.values()), default=0)
    if worst > restarts_total:
        failures.append(
            f"a twin resynced {worst} times across {restarts_total} "
            f"replica restarts (anti-entropy not converging to one "
            f"full pack per restart)"
        )
    snap = metrics.service_snapshot()
    artifact = {
        "bench": "fleet_twin",
        "n_twins": n_twins,
        "ever_active": len(ever_active),
        "replicas": n_replicas,
        "sim_s": round(clock.now(), 1),
        "wall_s": round(time.perf_counter() - t_wall, 2),
        "slo_ms": slo_ms,
        "capacity_curve": curve,
        "capacity_tenants_per_device_at_slo": capacity,
        "failover_convexity": fo_rows,
        "jain_fleet": round(jain_fleet, 4),
        "compile": {
            "hits": snap.get("compile_hits", 0),
            "misses": snap.get("compile_misses", 0),
        },
        "sheds_by_reason": _shed_totals(),
        "shed_total_metric": shed_metric,
        "shed_total_flight": shed_flight,
        "failovers_metric": fo_metric,
        "failovers_flight": fo_flight,
        "storm_hits_per_phase": storm_window_hits,
        "verified_selections": verified,
        "mismatches": mismatches[:8],
        "crashes": crashes,
        "resyncs_server": resyncs,
        "resyncs_twins": twin_resyncs,
        "wire_bytes_sent": sum(
            tw.wire_bytes_sent for tw in twins.values()
        ),
        "full_posts": sum(tw.full_posts for tw in twins.values()),
        "delta_posts": sum(tw.delta_posts for tw in twins.values()),
        "schedule_ticks": sum(
            tw.schedule_ticks for tw in twins.values()
        ),
        "resync_storm": storm_report,
        # the three headline storm numbers, flattened for dashboards
        # (bench.py's attestation covers them under these exact keys)
        "resync_storm_converge_ticks": storm_report.get(
            "converge_ticks", 0
        ),
        "resync_sheds": storm_report.get("resync_sheds", 0),
        "storm_p99_wait_ms": storm_report.get("p99_unaffected_ms", 0.0),
        "ok": not failures,
        "failures": failures,
    }
    return artifact


# ---------------------------------------------------------------------------
# deterministic shed-edge induction


def induce_shed_edges(seed: int = 0) -> dict:
    """Fire every admission-shed reason at least once, deterministically,
    against a dedicated single replica — and prove the two ledgers
    (labeled ``service_admission_shed_total`` vs the flight shed events
    grouped by the same reason attr) move in lockstep per label. The
    reason list is the REGISTRY's (``metrics.SHED_REASONS``), not a
    local literal: adding an admission edge to the service makes this
    pass FAIL until a recipe for inducing it exists here.

    The recipe leans on the replica being fully controllable here:
    a ``solve_hook`` that sleeps REAL time keeps the scheduler busy so
    queued victims age past real deadlines; the inflight cap and the
    queue timeout are mutable knobs; drain eviction uses a zero drain
    grace so ``drain_pending`` cannot serve what it should evict."""
    clock = FakeClock()
    spec0 = CONFIGS[2]
    cfg = ReschedulerConfig(
        resources=spec0.resources, solver="numpy",
        device_sick_threshold=0, service_drain_grace=0.0,
        planner_timeout=5.0,
    )
    srv = ServiceServer(
        cfg, "127.0.0.1:0", batch_window_s=0.0, max_inflight=4,
        clock=clock,
    )
    svc = srv.service
    real_sleep = {"s": 0.0}

    def hook(stacked, batch):
        if real_sleep["s"] > 0:
            time.sleep(real_sleep["s"])
        clock.advance(0.05)
        return svc._solve(stacked)

    svc.solve_hook = hook
    srv.start_background(scheduler=True)
    specs = fleet_specs(1, seed=seed)
    twin = TenantTwin(specs[0], cfg, clock, [f"http://{srv.address}"])
    packed, _ = twin.store.pack(twin.pdbs)
    body = wire.encode_plan_request("edge-probe", packed)
    url = f"http://{srv.address}/v2/plan"
    octet = {"Content-Type": "application/octet-stream"}

    before_metric = _shed_totals()
    # delta via event sequence numbers, not attr_counts diffs: the
    # event log is a bounded deque, and a full fleet run ahead of this
    # induction can make a before/after count diff see EVICTIONS of old
    # shed events as negative deltas. Events with seq > the start mark
    # are exactly the induced ones (far fewer than the log bound).
    seq0 = {
        kind: max(
            (e["seq"] for e in flight.events(kind)), default=0
        )
        for kind in SHED_FLIGHT_KINDS
    }
    got: Dict[str, str] = {}

    def post_expecting_503(
        headers: dict, label: str, payload: bytes = b""
    ) -> None:
        try:
            post_plan(url, payload or body, headers, timeout=15.0)
            got[label] = "served (expected 503)"
        except Exception as err:  # noqa: BLE001 — the 503 IS the
            # expected outcome here; anything else is reported in the
            # artifact, never raised out of the bench
            got[label] = str(err)

    def blocker(sleep_s: float) -> threading.Thread:
        real_sleep["s"] = sleep_s
        th = threading.Thread(
            target=post_expecting_503, args=(dict(octet), "blocker"),
        )
        th.start()
        time.sleep(0.15)  # let the scheduler pop the blocker batch
        return th

    # deadline: victim declares a 0.1s client deadline while the
    # device is busy 0.6s — evicted under the DEADLINE bound
    th = blocker(0.6)
    post_expecting_503(
        dict(octet, **{"X-Planner-Deadline": "0.1"}), "deadline"
    )
    th.join()
    real_sleep["s"] = 0.0
    # queue-timeout: same shape, but the SERVICE bound is the tight one
    old_qt = svc.queue_timeout_s
    svc.queue_timeout_s = 0.1
    th = blocker(0.6)
    post_expecting_503(dict(octet), "queue-timeout")
    th.join()
    svc.queue_timeout_s = old_qt
    real_sleep["s"] = 0.0
    # max-inflight: close the admission window entirely for one post
    srv.max_inflight = 0
    post_expecting_503(dict(octet), "max-inflight")
    srv.max_inflight = 4
    # resync-storm: a FINGERPRINTED full pack for a tenant this replica
    # has never cached is a resync-class ingest; with the ingest cap
    # forced to zero the admission class must refuse it typed (503 +
    # load-derived Retry-After, the dedicated ``resync-shed`` flight
    # kind) rather than let it crowd the delta queue
    old_cap = srv.resync_ingest_cap
    srv.resync_ingest_cap = 0
    post_expecting_503(
        dict(octet), "resync-storm",
        payload=wire.encode_plan_request(
            "edge-probe-uncached", packed,
            pack_fingerprint=pack_fingerprint(packed),
        ),
    )
    srv.resync_ingest_cap = old_cap
    # drain-refuse + drain-evict: park two victims in the queue with no
    # scheduler to serve them, start draining (new posts refused), then
    # drain_pending with ZERO grace must evict both
    svc.stop_scheduler()
    v1 = svc.submit_nowait("edge-probe", packed)
    v2 = svc.submit_nowait("edge-probe", packed)
    svc.begin_drain()
    post_expecting_503(dict(octet), "drain-refuse")
    svc.drain_pending()
    got["drain-evict"] = (
        "evicted" if (v1.error is not None and v2.error is not None)
        else "victims not evicted"
    )
    srv.close()

    metric_delta = {
        r: int(_shed_totals().get(r, 0) - before_metric.get(r, 0))
        for r in SHED_REASONS
    }
    flight_delta = {r: 0 for r in SHED_REASONS}
    for kind in SHED_FLIGHT_KINDS:
        for event in flight.events(kind):
            if event["seq"] <= seq0[kind]:
                continue
            reason = str(event.get("attrs", {}).get("reason", ""))
            if reason in flight_delta:
                flight_delta[reason] += 1
    failures = []
    for r in SHED_REASONS:
        if metric_delta[r] < 1:
            failures.append(f"edge {r} not induced ({got.get(r)})")
        if metric_delta[r] != flight_delta[r]:
            failures.append(
                f"edge {r}: metric delta {metric_delta[r]} != "
                f"flight delta {flight_delta[r]}"
            )
    return {
        "metric_delta": metric_delta,
        "flight_delta": flight_delta,
        "outcomes": got,
        "ok": not failures,
        "failures": failures,
    }
