"""The fleet twin: hundreds of tenant twins vs a real replica set on
one virtual clock — the harness that measures what no per-tick test
can.

The service era gives one TPU a fleet of tenants, but its proofs run
four agents. This module drives the design point: a heterogeneous
fleet of :class:`service.twin.TenantTwin` agents (mixed cluster-size
tiers, per-twin cadences and churn rates, zone-correlated spot storms,
tenants joining and leaving mid-run) against >= 2 real-HTTP
``ServiceServer`` replicas that share one ``FakeClock``. Wall time
stays in minutes because the device is MODELED: each replica's
``solve_hook`` advances the virtual clock by a per-batch cost
(base + per-lane) before running the numpy-oracle solve, so tenant
queue waits accrue in SIMULATED seconds and saturation emerges from
the same DRR queue / bucket batching / admission edges production
runs — while every served selection stays bit-identical to a solo
in-process plan (spot-checked continuously, serve-smoke's contract at
fleet scale).

Outputs (one JSON artifact line via ``bench.py --fleet-twin``):

- the **capacity-planning curve**: per load phase, device occupancy vs
  queue-wait p50/p99, and the derived tenants-per-device at the
  declared queue-wait SLO;
- **failover convexity**: a replica is killed (graceful) and restarted
  inside every phase; the p99 degradation during the kill window, per
  load level, measures how much headroom failover actually needs;
- **fairness**: Jain's index over per-twin served/offered shares;
- **compile sharing**: bucket-level first-compile hits/misses as twin
  shapes drift (storms change packed shapes mid-run);
- **admission-shed ledger**: every shed edge double-booked — the
  labeled metric vs the flight ``service-shed`` events — asserted
  equal, plus a deterministic per-reason edge-induction pass
  (:func:`induce_shed_edges`) that fires each of the five reasons at
  least once and diffs both surfaces per label.

``bench.py --fleet-twin-smoke`` runs the same loop at <= 64 twins
inside ``make check``; the full run (512 twins, one simulated hour)
is ``--fleet-twin``.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np

from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS
from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.service.server import ServiceServer
from k8s_spot_rescheduler_tpu.service.twin import (
    TenantTwin,
    fleet_specs,
    post_plan,
)
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log

SHED_REASONS = (
    "max-inflight", "queue-timeout", "drain-refuse", "deadline",
    "drain-evict",
)


def _pctl(values: List[float], q: float) -> float:
    """Nearest-rank percentile (same convention as the registry's
    windowed gauges, so the bench's curve and /healthz agree on what
    'p99' means)."""
    if not values:
        return 0.0
    ranked = sorted(values)
    import math

    idx = min(len(ranked) - 1, max(0, int(math.ceil(q * len(ranked))) - 1))
    return float(ranked[idx])


def _shed_totals() -> Dict[str, int]:
    return {
        k: int(v)
        for k, v in metrics.service_snapshot().get(
            "admission_shed", {}
        ).items()
    }


def _shed_delta(before: Dict[str, int]) -> Dict[str, int]:
    out = {}
    for reason, v in _shed_totals().items():
        d = v - before.get(reason, 0)
        if d:
            out[reason] = d
    return out


class _Fleet:
    """The replica set + bookkeeping one fleet run owns."""

    def __init__(self, cfg: ReschedulerConfig, clock: FakeClock,
                 n_replicas: int, max_inflight: int,
                 cost_base_s: float, cost_per_lane_s: float):
        self.cfg = cfg
        self.clock = clock
        self.max_inflight = max_inflight
        self.cost_base_s = cost_base_s
        self.cost_per_lane_s = cost_per_lane_s
        self.busy_s = [0.0] * n_replicas  # modeled device time, per slot
        # per-replica device frontier: the virtual time through which
        # that replica's modeled TPU is committed. Parallel replicas
        # must OVERLAP in virtual time (naively advancing the shared
        # clock by every batch cost would serialize the fleet's devices
        # and cap occupancy at 1/n); a batch starts at
        # max(its replica's frontier, its own last-enqueue time) and
        # the global clock only catches UP to frontiers, so each
        # device serializes its own batches while devices run
        # concurrently.
        self.frontier = [0.0] * n_replicas
        self._adv_lock = threading.Lock()
        self.replicas: List[Optional[ServiceServer]] = [None] * n_replicas
        self.addrs: List[str] = []
        for i in range(n_replicas):
            self.replicas[i] = self._spawn(i, "127.0.0.1:0")
            self.addrs.append(self.replicas[i].address)

    def _spawn(self, idx: int, addr: str) -> ServiceServer:
        srv = ServiceServer(
            self.cfg, addr, batch_window_s=0.0,
            max_inflight=self.max_inflight, clock=self.clock,
        )
        svc = srv.service
        clock = self.clock
        busy = self.busy_s

        def hook(stacked, batch):
            # the modeled TPU: virtual device time per batch, committed
            # against THIS replica's frontier so queue waits accrue in
            # simulated seconds while the numpy oracle keeps answers
            # bit-exact. The batch could not have started before its
            # last member enqueued — that lower bound (not clock.now(),
            # which a concurrent replica may already have advanced)
            # keeps parallel devices overlapped in virtual time.
            cost = self.cost_base_s + self.cost_per_lane_s * sum(
                r.lanes for r in batch
            )
            ready = max((r.enqueued for r in batch), default=0.0)
            with self._adv_lock:
                start = max(self.frontier[idx], ready)
                end = start + cost
                self.frontier[idx] = end
                behind = end - clock.now()
                if behind > 0:
                    clock.advance(behind)
            busy[idx] += cost
            return svc._solve(stacked)

        svc.solve_hook = hook
        srv.start_background(scheduler=True)
        return srv

    def kill(self, idx: int) -> None:
        srv = self.replicas[idx]
        if srv is not None:
            srv.graceful_shutdown()
            self.replicas[idx] = None

    def restart(self, idx: int) -> None:
        if self.replicas[idx] is None:
            self.replicas[idx] = self._spawn(idx, self.addrs[idx])

    def close(self) -> None:
        for i, srv in enumerate(self.replicas):
            if srv is not None:
                srv.graceful_shutdown()
                self.replicas[i] = None


def fleet_twin(
    n_twins: int = 512,
    n_replicas: int = 2,
    sim_s: float = 3600.0,
    seed: int = 0,
    slo_ms: float = 750.0,
    phases: int = 4,
    zones: int = 4,
    cost_base_s: float = 0.25,
    cost_per_lane_s: float = 0.004,
    storm_frac: float = 0.5,
    storm_len_s: float = 90.0,
    leave_frac: float = 0.05,
    max_inflight: int = 16,
    pool_workers: int = 32,
    verify_every: int = 7,
    jain_min: float = 0.8,
    max_wall_s: float = 280.0,
    deadline_frac: float = 0.0,
) -> dict:
    """Run the fleet twin; returns the capacity/observability artifact
    (``ok`` False plus a ``failures`` list when any fleet invariant
    broke). See the module docstring for what each phase does."""
    t_wall = time.perf_counter()
    clock = FakeClock()
    spec0 = CONFIGS[2]
    cfg = ReschedulerConfig(
        resources=spec0.resources, solver="numpy",
        device_sick_threshold=0, service_drain_grace=2.0,
        planner_timeout=5.0,
    )
    fleet = _Fleet(cfg, clock, n_replicas, max_inflight,
                   cost_base_s, cost_per_lane_s)
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner

    solo = SolverPlanner(cfg)
    specs = fleet_specs(n_twins, seed=seed, zones=zones,
                        deadline_frac=deadline_frac)
    rng = np.random.default_rng(seed ^ 0xF1EE7)
    twins: Dict[int, TenantTwin] = {}   # spec index -> twin (ever built)
    active: List[int] = []
    ever_active: set = set()
    next_fresh = 0                      # first never-activated spec index

    def activate(i: int) -> None:
        if i not in twins:
            order = [
                fleet.addrs[(i + k) % n_replicas]
                for k in range(n_replicas)
            ]
            twins[i] = TenantTwin(
                specs[i], cfg, clock,
                [f"http://{a}" for a in order],
            )
        tw = twins[i]
        tw.next_due = clock.now() + float(
            rng.uniform(0, tw.spec.cadence_s)
        )
        active.append(i)
        ever_active.add(i)

    mismatches: List[dict] = []
    verified = 0
    failures: List[str] = []
    curve: List[dict] = []
    fo_rows: List[dict] = []
    storm_window_hits: List[int] = []
    resync_before = metrics.service_snapshot()["delta_requests"].get(
        "resync", 0
    )
    shed_metric_0 = sum(_shed_totals().values())
    shed_flight_0 = flight.counts().get("service-shed", 0)
    fo_metric_0 = metrics.service_snapshot()["remote_planner_failover"]
    fo_flight_0 = flight.counts().get("failover", 0)

    pool = ThreadPoolExecutor(max_workers=pool_workers)
    phase_len = sim_s / phases
    aborted = ""
    try:
        for p in range(phases):
            phase_start = clock.now()
            phase_end = phase_start + phase_len
            target = int(np.ceil(n_twins * (p + 1) / phases))
            # tenant leave/join churn at the boundary: a slice of the
            # active set departs, replaced (plus the ramp) by fresh
            # twins — the service's bucket map must churn without any
            # delta-wire resync storm (asserted at the end)
            if p > 0 and leave_frac > 0 and active:
                n_leave = max(1, int(len(active) * leave_frac))
                for i in list(rng.choice(active, size=n_leave,
                                         replace=False)):
                    active.remove(int(i))
            while len(active) < target:
                if next_fresh < n_twins:
                    i, next_fresh = next_fresh, next_fresh + 1
                else:  # pool exhausted: rejoin a departed tenant
                    candidates = [
                        j for j in range(n_twins) if j not in active
                    ]
                    if not candidates:
                        break
                    i = int(rng.choice(candidates))
                if i not in active:
                    activate(i)
            metrics.reset_service_window()
            busy_mark = sum(fleet.busy_s)
            marks = {i: len(twins[i].wait_samples_ms) for i in active}
            served_mark = {i: twins[i].served for i in active}
            offered_mark = {i: twins[i].offered for i in active}
            shed_mark = _shed_totals()
            # disjoint scenario windows inside each phase: the storm
            # burst settles before the replica kill, so the failover
            # degradation is measured against steady state, not against
            # (or inside) the storm's own tail
            storm_at = phase_start + 0.45 * phase_len
            storm_restore_at = storm_at + min(
                storm_len_s, 0.15 * phase_len
            )
            fo_start = phase_start + 0.70 * phase_len
            fo_end = phase_start + 0.80 * phase_len
            kill_idx = p % n_replicas
            storm_zone = p % zones
            stormed: List[int] = []
            # actual fire times of the scenario windows: waits are
            # classified by request ENQUEUE time against these, so a
            # request queued during the outage counts against the
            # outage even when it is only served after the restart
            win: Dict[str, float] = {}
            fired = set()

            def fire_events(now: float) -> None:
                if "storm" not in fired and now >= storm_at:
                    fired.add("storm")
                    win["s0"] = now
                    hits = 0
                    for i in active:
                        tw = twins[i]
                        if tw.spec.zone != storm_zone:
                            continue
                        if tw.spot_interrupt(storm_frac):
                            hits += 1
                            stormed.append(i)
                            # interrupted capacity demands an immediate
                            # replan — the correlated burst the storm
                            # exists to model
                            tw.next_due = now + float(rng.uniform(0, 5))
                    storm_window_hits.append(hits)
                if "restore" not in fired and now >= storm_restore_at:
                    fired.add("restore")
                    win["s1"] = now
                    for i in stormed:
                        twins[i].spot_restore()
                if "kill" not in fired and now >= fo_start:
                    fired.add("kill")
                    win["f0"] = now
                    win["busy0"] = sum(
                        b for j, b in enumerate(fleet.busy_s)
                        if j != kill_idx
                    )
                    fleet.kill(kill_idx)
                if "restart" not in fired and now >= fo_end:
                    fired.add("restart")
                    win["f1"] = now
                    win["busy1"] = sum(
                        b for j, b in enumerate(fleet.busy_s)
                        if j != kill_idx
                    )
                    fleet.restart(kill_idx)

            def next_event_time() -> float:
                times = [phase_end]
                if "storm" not in fired:
                    times.append(storm_at)
                if "restore" not in fired:
                    times.append(storm_restore_at)
                if "kill" not in fired:
                    times.append(fo_start)
                if "restart" not in fired:
                    times.append(fo_end)
                return min(times)

            while clock.now() < phase_end:
                if time.perf_counter() - t_wall > max_wall_s:
                    aborted = (
                        "wall budget %.0fs exhausted in phase %d"
                        % (max_wall_s, p)
                    )
                    break
                now = clock.now()
                fire_events(now)
                due = [i for i in active if twins[i].next_due <= now]
                if not due:
                    nxt = min(
                        min(twins[i].next_due for i in active),
                        next_event_time(),
                    )
                    clock.advance(max(1e-3, nxt - now))
                    continue
                list(pool.map(lambda i: twins[i].tick(), due))
                for i in due:
                    tw = twins[i]
                    # bit-identity spot checks: every twin's first
                    # served tick, then a steady sample — BEFORE churn
                    # mutates the store the served plan was packed from
                    if tw.last_reply is not None and (
                        tw.served == 1 or tw.served % verify_every == 0
                    ):
                        bad = tw.verify(solo)
                        verified += 1
                        if bad is not None:
                            mismatches.append(bad)
                    # jittered cadence: a joint dispatch round must not
                    # phase-lock its cohort (identical next_due would
                    # turn every later round into one synchronized
                    # burst whose queue waits read as saturation at any
                    # load)
                    tw.next_due = clock.now() + tw.spec.cadence_s * (
                        float(tw.rng.uniform(0.7, 1.3))
                    )
                    tw.churn()
            if aborted:
                break
            # make sure phase events all fired even if the tick stream
            # went quiet near the boundary
            fire_events(clock.now())

            dur = max(1e-9, clock.now() - phase_start)
            occupancy = (sum(fleet.busy_s) - busy_mark) / (
                dur * n_replicas
            )
            healthy: List[float] = []
            storm_tail: List[float] = []
            failover: List[float] = []
            inf = float("inf")
            s0, s1 = win.get("s0", inf), win.get("s1", inf)
            f0, f1 = win.get("f0", inf), win.get("f1", inf)
            for i in active:
                tw = twins[i]
                a = marks.get(i, 0)
                # steady state excludes both scenario windows, so the
                # capacity curve and the failover baseline are not
                # polluted by the storm's own burst
                for t, w in zip(
                    tw.wait_sample_t[a:], tw.wait_samples_ms[a:]
                ):
                    if s0 <= t < s1:
                        storm_tail.append(w)
                    elif f0 <= t < f1:
                        failover.append(w)
                    else:
                        healthy.append(w)
            shares = [
                (twins[i].served - served_mark.get(i, 0))
                / max(1, twins[i].offered - offered_mark.get(i, 0))
                for i in active
                if twins[i].offered > offered_mark.get(i, 0)
            ]
            row = {
                "phase": p,
                "active_twins": len(active),
                "tenants_per_device": round(len(active) / n_replicas, 2),
                "occupancy": round(occupancy, 4),
                "queue_wait_p50_ms": round(_pctl(healthy, 0.50), 3),
                "queue_wait_p99_ms": round(_pctl(healthy, 0.99), 3),
                "queue_wait_p99_storm_ms": round(
                    _pctl(storm_tail, 0.99), 3
                ),
                "served": sum(
                    twins[i].served - served_mark.get(i, 0)
                    for i in active
                ),
                "jain": round(metrics.jain_fairness(shares), 4),
                "storm_hits": storm_window_hits[-1]
                if storm_window_hits else 0,
                "sheds": _shed_delta(shed_mark),
            }
            curve.append(row)
            mean_h = sum(healthy) / len(healthy) if healthy else 0.0
            mean_f = sum(failover) / len(failover) if failover else 0.0
            fo_dur = max(1e-9, win.get("f1", clock.now())
                         - win.get("f0", clock.now()))
            survivors = max(1, n_replicas - 1)
            surv_occ = (
                win.get("busy1", 0.0) - win.get("busy0", 0.0)
            ) / (fo_dur * survivors)
            fo_rows.append({
                "active_twins": len(active),
                "p99_healthy_ms": row["queue_wait_p99_ms"],
                "p99_failover_ms": round(_pctl(failover, 0.99), 3),
                "mean_healthy_ms": round(mean_h, 3),
                "mean_failover_ms": round(mean_f, 3),
                "degradation_ms": round(mean_f - mean_h, 3),
                # the robust convexity signal: how hot the surviving
                # replica(s) ran while one was down. Below saturation
                # the fleet absorbs a replica loss by consolidating
                # into bigger shared batches (waits can even DROP);
                # the loss of headroom shows up here first, and wait
                # degradation only goes positive once the survivor
                # pins at ~1.0
                "survivor_occupancy": round(surv_occ, 4),
                "failover_samples": len(failover),
            })
            log.info(
                "fleet-twin phase %d: active=%d occ=%.2f p99=%.0fms "
                "jain=%.3f sheds=%s",
                p, len(active), occupancy, row["queue_wait_p99_ms"],
                row["jain"], row["sheds"],
            )
    finally:
        pool.shutdown(wait=True)
        fleet.close()

    # ------------------------------------------------------------------
    # fleet invariants

    crashes = sum(tw.crashes for tw in twins.values())
    if aborted:
        failures.append(aborted)
    if crashes:
        failures.append(f"{crashes} twin crash(es)")
    if mismatches:
        failures.append(
            f"{len(mismatches)} selection mismatch(es) vs solo plans"
        )
    if len(ever_active) < min(n_twins, len(specs)):
        failures.append(
            f"only {len(ever_active)}/{n_twins} twins ever activated"
        )
    occ = [r["occupancy"] for r in curve]
    p99s = [r["queue_wait_p99_ms"] for r in curve]
    if len(curve) < phases:
        failures.append(f"only {len(curve)}/{phases} curve points")
    if any(b <= a for a, b in zip(occ, occ[1:])):
        failures.append(f"occupancy curve not increasing: {occ}")
    if curve and not p99s[-1] > p99s[0]:
        failures.append(
            f"degenerate queue-wait curve: p99 {p99s}"
        )
    if curve and p99s[0] > slo_ms:
        failures.append(
            f"lightest phase already violates the {slo_ms}ms SLO"
        )
    capacity = 0.0
    for r in curve:
        if r["queue_wait_p99_ms"] <= slo_ms:
            capacity = max(capacity, r["tenants_per_device"])
    all_shares = [
        tw.served / tw.offered
        for tw in twins.values() if tw.offered
    ]
    jain_fleet = metrics.jain_fairness(all_shares)
    if jain_fleet < jain_min:
        failures.append(
            f"fleet Jain {jain_fleet:.3f} < {jain_min}"
        )
    # double-booked degradation ledgers: cumulative flight event counts
    # vs the metric counters must agree exactly (shed + failover edges)
    shed_metric = sum(_shed_totals().values()) - shed_metric_0
    shed_flight = flight.counts().get("service-shed", 0) - shed_flight_0
    if shed_metric != shed_flight:
        failures.append(
            f"shed ledgers disagree: metric {shed_metric} != "
            f"flight {shed_flight}"
        )
    fo_metric = (
        metrics.service_snapshot()["remote_planner_failover"] - fo_metric_0
    )
    fo_flight = flight.counts().get("failover", 0) - fo_flight_0
    if fo_metric != fo_flight:
        failures.append(
            f"failover ledgers disagree: metric {fo_metric} != "
            f"flight {fo_flight}"
        )
    if fo_metric <= 0:
        failures.append("no failover edges induced by the kill windows")
    resyncs = (
        metrics.service_snapshot()["delta_requests"].get("resync", 0)
        - resync_before
    )
    if resyncs:
        failures.append(
            f"join/leave churn caused {resyncs} delta resyncs"
        )
    snap = metrics.service_snapshot()
    artifact = {
        "bench": "fleet_twin",
        "n_twins": n_twins,
        "ever_active": len(ever_active),
        "replicas": n_replicas,
        "sim_s": round(clock.now(), 1),
        "wall_s": round(time.perf_counter() - t_wall, 2),
        "slo_ms": slo_ms,
        "capacity_curve": curve,
        "capacity_tenants_per_device_at_slo": capacity,
        "failover_convexity": fo_rows,
        "jain_fleet": round(jain_fleet, 4),
        "compile": {
            "hits": snap.get("compile_hits", 0),
            "misses": snap.get("compile_misses", 0),
        },
        "sheds_by_reason": _shed_totals(),
        "shed_total_metric": shed_metric,
        "shed_total_flight": shed_flight,
        "failovers_metric": fo_metric,
        "failovers_flight": fo_flight,
        "storm_hits_per_phase": storm_window_hits,
        "verified_selections": verified,
        "mismatches": mismatches[:8],
        "crashes": crashes,
        "ok": not failures,
        "failures": failures,
    }
    return artifact


# ---------------------------------------------------------------------------
# deterministic shed-edge induction


def induce_shed_edges(seed: int = 0) -> dict:
    """Fire every admission-shed reason at least once, deterministically,
    against a dedicated single replica — and prove the two ledgers
    (labeled ``service_admission_shed_total`` vs flight ``service-shed``
    events grouped by the same reason attr) move in lockstep per label.

    The recipe leans on the replica being fully controllable here:
    a ``solve_hook`` that sleeps REAL time keeps the scheduler busy so
    queued victims age past real deadlines; the inflight cap and the
    queue timeout are mutable knobs; drain eviction uses a zero drain
    grace so ``drain_pending`` cannot serve what it should evict."""
    clock = FakeClock()
    spec0 = CONFIGS[2]
    cfg = ReschedulerConfig(
        resources=spec0.resources, solver="numpy",
        device_sick_threshold=0, service_drain_grace=0.0,
        planner_timeout=5.0,
    )
    srv = ServiceServer(
        cfg, "127.0.0.1:0", batch_window_s=0.0, max_inflight=4,
        clock=clock,
    )
    svc = srv.service
    real_sleep = {"s": 0.0}

    def hook(stacked, batch):
        if real_sleep["s"] > 0:
            time.sleep(real_sleep["s"])
        clock.advance(0.05)
        return svc._solve(stacked)

    svc.solve_hook = hook
    srv.start_background(scheduler=True)
    specs = fleet_specs(1, seed=seed)
    twin = TenantTwin(specs[0], cfg, clock, [f"http://{srv.address}"])
    packed, _ = twin.store.pack(twin.pdbs)
    body = wire.encode_plan_request("edge-probe", packed)
    url = f"http://{srv.address}/v2/plan"
    octet = {"Content-Type": "application/octet-stream"}

    before_metric = _shed_totals()
    # delta via event sequence numbers, not attr_counts diffs: the
    # event log is a bounded deque, and a full fleet run ahead of this
    # induction can make a before/after count diff see EVICTIONS of old
    # shed events as negative deltas. Events with seq > the start mark
    # are exactly the induced ones (far fewer than the log bound).
    seq0 = max(
        (e["seq"] for e in flight.events("service-shed")), default=0
    )
    got: Dict[str, str] = {}

    def post_expecting_503(headers: dict, label: str) -> None:
        try:
            post_plan(url, body, headers, timeout=15.0)
            got[label] = "served (expected 503)"
        except Exception as err:  # noqa: BLE001 — the 503 IS the
            # expected outcome here; anything else is reported in the
            # artifact, never raised out of the bench
            got[label] = str(err)

    def blocker(sleep_s: float) -> threading.Thread:
        real_sleep["s"] = sleep_s
        th = threading.Thread(
            target=post_expecting_503, args=(dict(octet), "blocker"),
        )
        th.start()
        time.sleep(0.15)  # let the scheduler pop the blocker batch
        return th

    # deadline: victim declares a 0.1s client deadline while the
    # device is busy 0.6s — evicted under the DEADLINE bound
    th = blocker(0.6)
    post_expecting_503(
        dict(octet, **{"X-Planner-Deadline": "0.1"}), "deadline"
    )
    th.join()
    real_sleep["s"] = 0.0
    # queue-timeout: same shape, but the SERVICE bound is the tight one
    old_qt = svc.queue_timeout_s
    svc.queue_timeout_s = 0.1
    th = blocker(0.6)
    post_expecting_503(dict(octet), "queue-timeout")
    th.join()
    svc.queue_timeout_s = old_qt
    real_sleep["s"] = 0.0
    # max-inflight: close the admission window entirely for one post
    srv.max_inflight = 0
    post_expecting_503(dict(octet), "max-inflight")
    srv.max_inflight = 4
    # drain-refuse + drain-evict: park two victims in the queue with no
    # scheduler to serve them, start draining (new posts refused), then
    # drain_pending with ZERO grace must evict both
    svc.stop_scheduler()
    v1 = svc.submit_nowait("edge-probe", packed)
    v2 = svc.submit_nowait("edge-probe", packed)
    svc.begin_drain()
    post_expecting_503(dict(octet), "drain-refuse")
    svc.drain_pending()
    got["drain-evict"] = (
        "evicted" if (v1.error is not None and v2.error is not None)
        else "victims not evicted"
    )
    srv.close()

    metric_delta = {
        r: int(_shed_totals().get(r, 0) - before_metric.get(r, 0))
        for r in SHED_REASONS
    }
    flight_delta = {r: 0 for r in SHED_REASONS}
    for event in flight.events("service-shed"):
        if event["seq"] <= seq0:
            continue
        reason = str(event.get("attrs", {}).get("reason", ""))
        if reason in flight_delta:
            flight_delta[reason] += 1
    failures = []
    for r in SHED_REASONS:
        if metric_delta[r] < 1:
            failures.append(f"edge {r} not induced ({got.get(r)})")
        if metric_delta[r] != flight_delta[r]:
            failures.append(
                f"edge {r}: metric delta {metric_delta[r]} != "
                f"flight delta {flight_delta[r]}"
            )
    return {
        "metric_delta": metric_delta,
        "flight_delta": flight_delta,
        "outcomes": got,
        "ok": not failures,
        "failures": failures,
    }
