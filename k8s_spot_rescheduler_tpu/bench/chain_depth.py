"""Chain-depth DEMAND analysis (VERDICT r4 #4).

The repair search executes relocation chains up to depth 2; three-link
chains are the published quality boundary (docs/RESULTS.md, `chain3`
pools: shipped 0.750 of the ILP by construction). The open question was
empirical: how deep a chain does the optimum ACTUALLY need on organic
problems? This module measures it. For every candidate lane of every
tick of a run, classify the MINIMUM mechanism that proves the lane's
drain:

- ``greedy``  — first-fit or best-fit proves it (depth 0);
- ``depth1``  — the depth-1-only repair variant proves it
  (``plan_repair(..., chain=False)``) — one relocation, no chain;
- ``depth2``  — the shipped depth-2 chained search proves it;
- ``deeper``  — the single-lane ILP proves the drain possible but the
  depth-2 search cannot find it: demand for depth ≥ 3 (or for a
  different depth-≤2 move sequence outside the rotation schedule —
  either way, the shipped stack loses this lane);
- ``infeasible`` — the ILP proves no valid placement exists at all.

The expensive ILP only runs on lanes the cheap passes left unresolved,
so organic runs (where ``deeper`` is the rare case being hunted) stay
fast. Results feed the RESULTS.md chain-depth-demand table: if
``deeper`` is zero across every organic run, the published chain3
boundary is evidence-backed; if it is real, the chain election needs a
depth-k extension.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Optional

import numpy as np

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster

# the single-lane view (C=1) is exact because lanes are independent
# fork copies — same argument as the MULTICHIP oracle slices; one
# shared slicer (solver/schedule.py) serves this analyzer and the
# schedule execution handle's per-step validation
from k8s_spot_rescheduler_tpu.solver.schedule import (  # noqa: F401
    slice_lane as _slice_lane,
)


def classify_packed(
    packed: PackedCluster,
    *,
    rounds: int = 8,
    ilp_time_limit: float = 60.0,
) -> Counter:
    """Per-lane minimal-mechanism classification for one tick's problem.

    Device passes run jitted (greedy, depth-1, depth-2) over all lanes
    at once; the per-lane ILP (bench/quality.ilp_max_drains on a C=1
    slice) runs only for lanes depth-2 left unproven."""
    from k8s_spot_rescheduler_tpu.bench.quality import ilp_max_drains
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd_jit
    from k8s_spot_rescheduler_tpu.solver.repair import plan_repair_jit

    valid = np.asarray(packed.cand_valid)
    counts: Counter = Counter()
    if not valid.any():
        return counts
    ff = np.asarray(plan_ffd_jit(packed).feasible)
    bf = np.asarray(plan_ffd_jit(packed, best_fit=True).feasible)
    greedy = ff | bf
    d1 = np.asarray(
        plan_repair_jit(packed, rounds=rounds, chain=False).feasible
    )
    d2 = np.asarray(plan_repair_jit(packed, rounds=rounds).feasible)
    for c in np.flatnonzero(valid):
        if greedy[c]:
            counts["greedy"] += 1
        elif d1[c]:
            counts["depth1"] += 1
        elif d2[c]:
            counts["depth2"] += 1
        else:
            ilp = ilp_max_drains(
                _slice_lane(packed, int(c)), time_limit=ilp_time_limit
            )
            if ilp is None:
                counts["ilp-failed"] += 1
            elif ilp > 0:
                counts["deeper"] += 1
            else:
                counts["infeasible"] += 1
    return counts


class _PackedTap:
    """Collects each planner tick's packed problem id-deduplicated, so a
    drive loop can classify exactly the problems the controller solved."""

    def __init__(self) -> None:
        self.counts: Counter = Counter()
        self.ticks = 0
        self._last_id: Optional[int] = None

    def __call__(self, packed: Optional[PackedCluster]) -> None:
        if packed is None or id(packed) == self._last_id:
            return
        self._last_id = id(packed)
        self.ticks += 1
        self.counts += classify_packed(packed)


def analyze_quality_runs(
    seeds=range(3), configs: Optional[Dict] = None
) -> Dict[str, Counter]:
    """Chain-depth demand over the organic quality configs: every tick
    of every drain-to-exhaustion run, every valid lane classified.
    Returns {config name: Counter}. The chain3 BOUNDARY config is the
    deliberate positive control (its lanes demand depth 3 by
    construction); it is reported separately by the bench mode, never
    mixed into the organic rows."""
    from k8s_spot_rescheduler_tpu.bench.quality import drain_to_exhaustion
    from k8s_spot_rescheduler_tpu.io.synthetic import (
        QUALITY_CONFIGS,
        generate_quality_cluster,
    )
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    out: Dict[str, Counter] = {}
    for name, spec in (configs or QUALITY_CONFIGS).items():
        total: Counter = Counter()
        for seed in seeds:
            tap = _PackedTap()
            client = generate_quality_cluster(
                spec, seed, reschedule_evicted=True
            )
            drain_to_exhaustion(
                client,
                ReschedulerConfig(solver="numpy", resources=spec.resources),
                on_packed=tap,
            )
            total += tap.counts
        out[name] = total
    return out


def analyze_replay(
    *, n_events: int = 300, seed: int = 0, constrained: bool = True
) -> Counter:
    """Chain-depth demand under churn: the constrained replay (spot
    interruptions × the full predicate surface), every tick's lanes
    classified."""
    from k8s_spot_rescheduler_tpu.bench.replay import run_replay
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    tap = _PackedTap()
    run_replay(
        ReschedulerConfig(solver="numpy"),
        n_events=n_events,
        seed=seed,
        constrained=constrained,
        on_packed=tap,
    )
    return tap.counts
