"""Narrow-int carry layouts: the per-spot scan state, sized honestly.

The greedy passes' mutable per-(lane, spot) state — capacity consumed,
pods placed, dynamic affinity bits accumulated — was historically
carried WIDE (f32 free, i32 count, u32×A affinity words, the static
spot rows broadcast into every lane's copy). Those carries, not the
repair temporaries, set the fully-chunked scaling ceiling (docs/
RESULTS.md "scaling"): every greedy pass holds them, double-buffered
through the ``lax.scan``, and no spot chunking shrinks them.

This module is the host half of the ROADMAP-5 answer:

- the carries become DELTAS against the static spot rows (consumed, not
  free; placements added, not absolute count; pod-contributed affinity
  bits, not static|dynamic) — the statics are read-only scan inputs, so
  each delta starts at zero and stays bounded by what ONE lane can do
  to one node;
- those bounds are computable EXACTLY on the host from the pack:
  consumed ≤ the lane's total valid request, placements ≤ K, dynamic
  affinity bits ⊆ the OR of every pod's interned words. ``carry_layout``
  derives the narrowest int dtypes those bounds provably fit —
  int16/int8/uint16 at production shapes — and the kernels widen ON
  READ at one site, so the selection arithmetic (f32 integers < 2**24,
  exact) is bit-identical to the wide layout;
- when a pack's bounds exceed a narrow dtype (adversarial requests,
  K > 127, affinity bits interned past bit 15) the layout falls back
  per-field to the wide dtype — the guard is exact, never heuristic,
  so narrowing can never change a single placement.

Kept free of jax imports on purpose: ``solver/memory.py`` (the HBM
dispatch estimator) and the kernels both consume it, and the estimator
must stay importable host-side without touching a backend.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class CarryLayout(NamedTuple):
    """Dtypes of the three mutable carry planes (delta form).

    ``used``  — capacity consumed per (lane, resource, spot);
    ``count`` — placements added per (lane, spot);
    ``aff``   — OR of placed pods' affinity bits per (lane, word, spot).

    The default is the WIDE layout: delta-form but full-width dtypes,
    arithmetically identical to the historical absolute-value carries
    (all quantities are exact integers in f32 below 2**24).
    """

    used: str = "float32"
    count: str = "int32"
    aff: str = "uint32"


WIDE_LAYOUT = CarryLayout()

# The layout the 20x dispatch ladder targets (and the jaxpr auditor
# traces at MAX_SHAPES): int16 consumed quanta, int8 placement deltas,
# uint16 dynamic-affinity words. carry_layout() only ever RETURNS this
# when the pack's exact bounds fit it.
NARROW_LAYOUT = CarryLayout(used="int16", count="int8", aff="uint16")


def carry_layout(packed) -> CarryLayout:
    """The narrowest layout ``packed``'s exact host-side bounds fit.

    Works on the host copy of a PackedCluster (numpy arrays; device
    arrays are converted). Exactness argument per field:

    - ``used[c, r, s]`` is always the sum of ``slot_req[c, k, r]`` over
      the pods of lane ``c`` currently assigned to ``s`` (the partial
      pass adds; repair moves, keeping the invariant), so it is bounded
      by the lane's total valid request per resource;
    - ``count[c, s]`` delta is the number of lane-``c`` pods on ``s``,
      bounded by K;
    - ``aff[c, a, s]`` delta is an OR of ``slot_aff`` words, so every
      set bit appears in the OR over all slots.
    """
    req = np.asarray(packed.slot_req)
    valid = np.asarray(packed.slot_valid)
    consumed_max = 0.0
    if req.size:
        consumed_max = float(
            (req * valid[:, :, None].astype(req.dtype)).sum(axis=1).max()
        )
    if consumed_max <= np.iinfo(np.int16).max:
        used = "int16"
    elif consumed_max <= np.iinfo(np.uint16).max:
        # consumed is invariantly >= 0 (the sum of currently-assigned
        # requests), so the unsigned range is safe — it covers e.g. a
        # fully-packed 64 GiB node's MiB-unit memory sums that int16
        # cannot (updates widen->compute->narrow, never cast a negative
        # intermediate)
        used = "uint16"
    else:
        used = "float32"  # exact up to 2**24, the pack contract
    K = req.shape[1] if req.ndim == 3 else 0
    count = "int8" if K <= np.iinfo(np.int8).max else "int16"
    slot_aff = np.asarray(packed.slot_aff)
    aff_bits = (
        int(np.bitwise_or.reduce(slot_aff, axis=None)) if slot_aff.size else 0
    )
    if aff_bits <= 0xFF:
        aff = "uint8"
    elif aff_bits <= 0xFFFF:
        aff = "uint16"
    else:
        aff = "uint32"
    return CarryLayout(used=used, count=count, aff=aff)


def plane_bytes(layout: CarryLayout, R: int, A: int) -> int:
    """Carry bytes per (lane, spot) under ``layout``: R used planes +
    one count plane + A affinity planes. The wide layout reproduces the
    historical 4*(R + A + 1); the full narrow layout is 2R + 2A + 1."""
    return (
        R * np.dtype(layout.used).itemsize
        + np.dtype(layout.count).itemsize
        + A * np.dtype(layout.aff).itemsize
    )


def is_narrow(layout: CarryLayout) -> bool:
    """True when any carry plane is narrower than the wide layout."""
    return layout != WIDE_LAYOUT
