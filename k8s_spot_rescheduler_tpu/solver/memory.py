"""Single-chip HBM guard + mesh-shard dispatch decision.

The fused union program (first-fit ∪ best-fit ∪ repair,
solver/fallback.py) carries per-candidate spot-pool state: [C, R, S]
free, [C, S] count, [C, A, S] affinity — double-buffered through the
``lax.scan``, plus the per-step boolean/slack temporaries. Even though
the best-fit and repair passes *run* only under ``lax.cond``, XLA still
allocates their buffers, so the program's footprint is set by these
carries regardless of runtime skipping. Past ~4× north-star scale the
allocation exceeds a v5e's HBM at compile time (docs/RESULTS.md "Scaling
past the north star").

The designed answer is the mesh-sharded solver
(parallel/sharded_ffd.py): candidate and spot axes shard over the
device mesh, dividing the carry footprint by the device count. This
module is the dispatch decision: *estimate* the single-chip footprint
from the packed shapes, compare against the device budget, and tell the
planner when to reroute (SolverPlanner auto-dispatch; SURVEY.md §5.7 —
cluster size is this framework's "long context", and the mesh is how it
scales past one chip, replacing the reference's serial O(P×N) nest,
rescheduler.go:334-370).

The estimate is deliberately simple and pinned by tests against the
measured reality (4× fits a 16 GB chip, 8× does not —
tests/test_sharding.py)."""

from __future__ import annotations

from typing import NamedTuple, Optional

from k8s_spot_rescheduler_tpu.solver.carry import (
    NARROW_LAYOUT,
    plane_bytes as carry_plane_bytes_of,
)

# Default assumed HBM when the backend won't say (v5e = 16 GB);
# fraction left to the solver after runtime/program overheads.
DEFAULT_HBM_BYTES = 16 * 1024**3
BUDGET_FRACTION = 0.85

# A repair spot chunk narrower than the TPU lane width stops paying:
# every [C, Sc] temporary pads back up to 128 lanes in VMEM/HBM tiles.
MIN_REPAIR_CHUNK = 128
MIN_CARRY_CHUNK = MIN_REPAIR_CHUNK  # same tiling argument, carry tier


def estimate_union_hbm_breakdown(
    C: int, K: int, S: int, R: int, W: int, A: int,
    repair_spot_chunks: int = 1,
    carry_chunks: int = 0,
    carry_plane_bytes: Optional[int] = None,
) -> dict:
    """Per-component HBM estimate of the fused union solver: named
    buffer family -> bytes. ``estimate_union_hbm_bytes`` is the sum.

    Dominant terms: the scan ``carries`` — one [C, S] plane per resource
    (free), per affinity word (aff), plus one (count) — double-buffered
    by the scan (x2), plus ~3 per-step ``temporaries`` planes (fit mask,
    slack, onehot live ranges); then the ``repair`` rounds' working set —
    the unlocker probe, the two first-fit re-placement sweeps, the
    [C, R, S] commit delta and the affinity rewrite intermediates, about
    (R + 2A + 7) live [C, S] planes; then the scan ``slots`` inputs and
    the assignment ``outputs``. ``spot_static`` rows are O(S) and
    negligible but included for completeness.

    The named split exists for the jaxpr-tier ``memory-reconcile`` pass
    (tools/analysis/jaxpr): when the estimate drifts from the traced
    program, the finding names WHICH component drifted, not just the
    sum.

    ``repair_spot_chunks`` > 1 models the elect-then-commit chunked
    repair (solver/repair.plan_repair_chunked): only one spot chunk's
    round temporaries are live at a time, so that term divides by the
    chunk count — the carries (which every greedy pass needs too) do
    not, which is what set the OLD fully-chunked ceiling.
    ``repair_spot_chunks=0`` models a program with NO repair phase at
    all (``fallback_best_fit`` off or ``repair_rounds=0``): the repair
    working set is never allocated, so charging it would reroute such
    configs off one chip for memory they never use.

    ``carry_chunks`` >= 1 models the CARRY-STREAMED union
    (solver/fallback.with_repair_streamed, ROADMAP 5): the greedy scan
    state is the narrow DELTA carry (``carry_plane_bytes`` per
    (lane, spot) — solver/carry.plane_bytes of the pack's guarded
    layout; the NARROW_LAYOUT default when unspecified), double-buffered
    like every scan carry, and the first-fit pass's resident chunk,
    per-step temporaries and repair working set all live one spot chunk
    at a time, so those terms divide by the carry-chunk count. The
    carries term does NOT divide — best-fit's global election and the
    repair rounds keep the stacked state — which is why the new ceiling
    sits at the NARROW carry bound rather than the wide one.
    """
    plane = C * S * 4  # one f32/i32/u32 [C, S] plane
    if carry_chunks and carry_chunks >= 1:
        npb = (
            carry_plane_bytes
            if carry_plane_bytes
            else carry_plane_bytes_of(NARROW_LAYOUT, R, A)
        )
        Sc = -(-S // carry_chunks)
        cplane = C * Sc * 4  # one chunk-resident f32 [C, Sc] plane
        return {
            # stacked narrow delta state (best-fit + repair rounds),
            # double-buffered by the scan — the new, smaller sharp term
            "carries": 2 * npb * C * S,
            # per-chunk step temporaries only: the elect-then-commit
            # map's restacked copy is a liveness-model artifact (XLA
            # ping-pongs the scan carry's two buffers; the measured
            # hardware envelope has always tracked the estimator, not
            # the liveness peak — memory-reconcile's TOTAL_BAND lower
            # edge is calibrated to 0.20 for exactly this shape)
            "temporaries": 3 * cplane,
            "repair": (
                0
                if repair_spot_chunks == 0
                else (R + 2 * A + 7) * cplane
            ),
            "slots": K * C * (R * 4 + 1 + W * 4 + A * 4),
            "outputs": 2 * C * K * 4,
            "spot_static": S * (R * 4 + 4 + 4 + W * 4 + 1 + A * 4),
        }
    return {
        "carries": 2 * (R + A + 1) * plane,  # double-buffered scan state
        "temporaries": 3 * plane,
        "repair": (
            0
            if repair_spot_chunks == 0
            else (R + 2 * A + 7) * plane // repair_spot_chunks
        ),
        "slots": K * C * (R * 4 + 1 + W * 4 + A * 4),
        "outputs": 2 * C * K * 4,  # chosen [K, C] + assignment [C, K]
        "spot_static": S * (R * 4 + 4 + 4 + W * 4 + 1 + A * 4),
    }


def estimate_union_hbm_bytes(
    C: int, K: int, S: int, R: int, W: int, A: int,
    repair_spot_chunks: int = 1,
    carry_chunks: int = 0,
    carry_plane_bytes: Optional[int] = None,
) -> int:
    """Estimated peak HBM of the fused union solver at these shapes
    (sum of ``estimate_union_hbm_breakdown`` — see there for the
    component model)."""
    return sum(
        estimate_union_hbm_breakdown(
            C, K, S, R, W, A,
            repair_spot_chunks=repair_spot_chunks,
            carry_chunks=carry_chunks,
            carry_plane_bytes=carry_plane_bytes,
        ).values()
    )


def pick_repair_chunks(
    C: int, K: int, S: int, R: int, W: int, A: int, budget_bytes: int
) -> int:
    """Spot-chunk count for the repair phase at these shapes.

    1 = the unchunked union program already fits ``budget_bytes``;
    >1 = the smallest power-of-two chunking (each chunk kept at least
    MIN_REPAIR_CHUNK spots wide) whose per-round working set fits;
    0 = even fully chunked the residual scan carries exceed the budget
    — the regime of the 2-D cand×spot tier, where the repair phase is
    genuinely unavailable and ``repair_unavailable`` must fire.

    Chunk counts are powers of two only (one compiled program per
    count, O(log S) of them at most — the same recompile-bounding
    discipline as the delta pads), and each chunk must come out at
    least MIN_REPAIR_CHUNK spots wide (``ceil(S / n)``, matching the
    padding ``plan_repair_chunked`` itself applies).
    """
    n = 1
    while True:
        est = estimate_union_hbm_bytes(
            C, K, S, R, W, A, repair_spot_chunks=n
        )
        if est <= budget_bytes:
            return n
        n *= 2
        if -(-S // n) < MIN_REPAIR_CHUNK:
            return 0


def pick_carry_chunks(
    C: int, K: int, S: int, R: int, W: int, A: int, budget_bytes: int,
    carry_plane_bytes: Optional[int] = None,
) -> int:
    """Carry-chunk count for the carry-streamed union at these shapes.

    1 = the narrow-carry program fits ``budget_bytes`` without spot
    streaming; >1 = the smallest power-of-two chunking (each chunk kept
    at least MIN_CARRY_CHUNK spots wide) whose estimate fits; 0 = even
    fully streamed the narrow stacked carries exceed the budget — the
    regime of the 2-D cand×spot tier, where the repair phase is
    genuinely unavailable and ``repair_unavailable`` must fire.

    ``carry_plane_bytes`` is the pack's guarded layout width
    (solver/carry.plane_bytes of carry_layout(packed)); the chunk-count
    discipline mirrors ``pick_repair_chunks`` (powers of two, one
    compiled program per count)."""
    n = 1
    while True:
        est = estimate_union_hbm_bytes(
            C, K, S, R, W, A,
            repair_spot_chunks=n,
            carry_chunks=n,
            carry_plane_bytes=carry_plane_bytes,
        )
        if est <= budget_bytes:
            return n
        n *= 2
        if -(-S // n) < MIN_CARRY_CHUNK:
            return 0


class TierDecision(NamedTuple):
    """The dispatch ladder's verdict at one problem's shapes — the ONE
    decision ``planner/solver_planner._maybe_shard``, ``bench.py`` and
    ``make scale-smoke`` all read, so they can never drift.

    ``kind``: "single" (configured single-chip program), "cand"
    (cand-sharded union, repair unchunked), "cand-chunked" (cand tier,
    spot-chunked repair), "cand-carry" (cand tier, narrow delta carries
    + spot streaming — the ROADMAP-5 rung), "2d" (cand×spot, repair
    unavailable). ``repair_chunks`` is the spot-chunk count the repair
    phase runs with (0 = no repair on this tier); ``carry_chunks`` > 0
    only on the carry tier. ``est_bytes`` is the per-device estimate of
    the dispatched program; ``carry_bytes`` its resident scan-carry
    component (the "carries" term); ``lane_block`` the per-device lane
    count on the sharded tiers."""

    kind: str
    repair_chunks: int
    carry_chunks: int
    est_bytes: int
    carry_bytes: int
    lane_block: int
    repair_unavailable: bool


def pick_tier(
    C: int, K: int, S: int, R: int, W: int, A: int,
    *,
    n_devices: int,
    budget_bytes: Optional[int] = None,
    wants_repair: bool = True,
    carry_plane_bytes: Optional[int] = None,
    forced_carry_chunks: int = 0,
) -> TierDecision:
    """Walk the dispatch ladder at these shapes: single-chip →
    cand-sharded (repair intact) → cand-sharded + spot-chunked repair →
    cand-sharded + carry-streamed narrow union → 2-D (repair
    unavailable). ``forced_carry_chunks`` (the ``carry_chunks`` config
    knob) pins the carry tier's chunk count instead of
    ``pick_carry_chunks``; 0 = auto. ``carry_plane_bytes`` may be a
    zero-arg callable (the pack's exact layout guard is an O(C·K·R)
    host pass — deferring it keeps the common under-budget tick from
    paying it)."""
    budget = budget_bytes if budget_bytes else device_hbm_budget()
    own_chunks = 1 if wants_repair else 0

    def est(c, **kw):
        return estimate_union_hbm_bytes(c, K, S, R, W, A, **kw)

    def bd(c, **kw):
        return estimate_union_hbm_breakdown(c, K, S, R, W, A, **kw)

    full = est(C, repair_spot_chunks=own_chunks)
    if n_devices <= 1 or full <= budget:
        return TierDecision(
            "single", own_chunks, 0, full,
            bd(C, repair_spot_chunks=own_chunks)["carries"], C, False,
        )
    lane = -(-C // n_devices)
    lane_est = est(lane, repair_spot_chunks=own_chunks)
    if lane_est <= budget:
        return TierDecision(
            "cand", own_chunks, 0, lane_est,
            bd(lane, repair_spot_chunks=own_chunks)["carries"], lane, False,
        )
    chunks = (
        pick_repair_chunks(lane, K, S, R, W, A, budget)
        if wants_repair
        else 0
    )
    if chunks > 1:
        return TierDecision(
            "cand-chunked", chunks, 0,
            est(lane, repair_spot_chunks=chunks),
            bd(lane, repair_spot_chunks=chunks)["carries"], lane, False,
        )
    if wants_repair:
        cpb = (
            carry_plane_bytes()
            if callable(carry_plane_bytes)
            else carry_plane_bytes
        )
        cchunks = forced_carry_chunks or pick_carry_chunks(
            lane, K, S, R, W, A, budget, carry_plane_bytes=cpb,
        )
        if cchunks >= 1:
            kw = dict(
                repair_spot_chunks=cchunks,
                carry_chunks=cchunks,
                carry_plane_bytes=cpb,
            )
            return TierDecision(
                "cand-carry", cchunks, cchunks, est(lane, **kw),
                bd(lane, **kw)["carries"], lane, False,
            )
    return TierDecision(
        "2d", 0, 0, est(lane, repair_spot_chunks=0),
        bd(lane, repair_spot_chunks=0)["carries"], lane, wants_repair,
    )


def packed_shapes(packed) -> tuple:
    """(C, K, S, R, W, A) from a PackedCluster (host or device arrays)."""
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    W = packed.spot_taints.shape[1]
    A = packed.spot_aff.shape[1]
    return C, K, S, R, W, A


def device_hbm_budget(device=None) -> int:
    """The per-device byte budget: ``bytes_limit`` from the backend's
    memory stats when available (TPU runtimes publish it), else the
    v5e default — scaled by the budget fraction."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats() or {}
        limit = int(stats.get("bytes_limit") or 0)
    except Exception:  # noqa: BLE001 — CPU/older runtimes: no stats
        limit = 0
    return int((limit or DEFAULT_HBM_BYTES) * BUDGET_FRACTION)


def should_shard(
    packed,
    n_devices: int,
    *,
    budget_bytes: Optional[int] = None,
    repair_spot_chunks: int = 1,
) -> bool:
    """True when the union program won't fit one chip AND a mesh exists
    to shard it over. With one device this is always False — the caller
    keeps the single-chip path and its honest OOM.
    ``repair_spot_chunks=0`` = the configured program has no repair
    phase (its working set must not count against the chip)."""
    if n_devices <= 1:
        return False
    budget = budget_bytes if budget_bytes else device_hbm_budget()
    est = estimate_union_hbm_bytes(
        *packed_shapes(packed), repair_spot_chunks=repair_spot_chunks
    )
    return est > budget
