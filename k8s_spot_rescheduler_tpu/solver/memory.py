"""Single-chip HBM guard + mesh-shard dispatch decision.

The fused union program (first-fit ∪ best-fit ∪ repair,
solver/fallback.py) carries per-candidate spot-pool state: [C, R, S]
free, [C, S] count, [C, A, S] affinity — double-buffered through the
``lax.scan``, plus the per-step boolean/slack temporaries. Even though
the best-fit and repair passes *run* only under ``lax.cond``, XLA still
allocates their buffers, so the program's footprint is set by these
carries regardless of runtime skipping. Past ~4× north-star scale the
allocation exceeds a v5e's HBM at compile time (docs/RESULTS.md "Scaling
past the north star").

The designed answer is the mesh-sharded solver
(parallel/sharded_ffd.py): candidate and spot axes shard over the
device mesh, dividing the carry footprint by the device count. This
module is the dispatch decision: *estimate* the single-chip footprint
from the packed shapes, compare against the device budget, and tell the
planner when to reroute (SolverPlanner auto-dispatch; SURVEY.md §5.7 —
cluster size is this framework's "long context", and the mesh is how it
scales past one chip, replacing the reference's serial O(P×N) nest,
rescheduler.go:334-370).

The estimate is deliberately simple and pinned by tests against the
measured reality (4× fits a 16 GB chip, 8× does not —
tests/test_sharding.py)."""

from __future__ import annotations

from typing import Optional

# Default assumed HBM when the backend won't say (v5e = 16 GB);
# fraction left to the solver after runtime/program overheads.
DEFAULT_HBM_BYTES = 16 * 1024**3
BUDGET_FRACTION = 0.85


def estimate_union_hbm_bytes(
    C: int, K: int, S: int, R: int, W: int, A: int
) -> int:
    """Estimated peak HBM of the fused union solver at these shapes.

    Dominant terms: the scan carries — one [C, S] plane per resource
    (free), per affinity word (aff), plus one (count) — double-buffered
    by the scan (x2), plus ~3 per-step temporary planes (fit mask,
    slack, onehot live ranges); then the scan slot inputs and the
    assignment outputs. Spot-static rows are O(S) and negligible but
    included for completeness.
    """
    plane = C * S * 4  # one f32/i32/u32 [C, S] plane
    carries = 2 * (R + A + 1) * plane  # double-buffered scan state
    temporaries = 3 * plane
    slots = K * C * (R * 4 + 1 + W * 4 + A * 4)
    outputs = 2 * C * K * 4  # chosen [K, C] + assignment [C, K]
    spot_static = S * (R * 4 + 4 + 4 + W * 4 + 1 + A * 4)
    return carries + temporaries + slots + outputs + spot_static


def packed_shapes(packed) -> tuple:
    """(C, K, S, R, W, A) from a PackedCluster (host or device arrays)."""
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    W = packed.spot_taints.shape[1]
    A = packed.spot_aff.shape[1]
    return C, K, S, R, W, A


def device_hbm_budget(device=None) -> int:
    """The per-device byte budget: ``bytes_limit`` from the backend's
    memory stats when available (TPU runtimes publish it), else the
    v5e default — scaled by the budget fraction."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats() or {}
        limit = int(stats.get("bytes_limit") or 0)
    except Exception:  # noqa: BLE001 — CPU/older runtimes: no stats
        limit = 0
    return int((limit or DEFAULT_HBM_BYTES) * BUDGET_FRACTION)


def should_shard(
    packed,
    n_devices: int,
    *,
    budget_bytes: Optional[int] = None,
) -> bool:
    """True when the union program won't fit one chip AND a mesh exists
    to shard it over. With one device this is always False — the caller
    keeps the single-chip path and its honest OOM."""
    if n_devices <= 1:
        return False
    budget = budget_bytes if budget_bytes else device_hbm_budget()
    return estimate_union_hbm_bytes(*packed_shapes(packed)) > budget
