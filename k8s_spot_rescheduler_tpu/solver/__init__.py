"""Drain-plan solvers."""

from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.solver.ffd import SolveResult, plan_ffd, plan_ffd_jit
from k8s_spot_rescheduler_tpu.solver.select import make_fused_planner

__all__ = [
    "plan_oracle",
    "SolveResult",
    "plan_ffd",
    "plan_ffd_jit",
    "make_fused_planner",
]
