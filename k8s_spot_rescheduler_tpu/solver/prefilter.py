"""Cheap per-lane infeasibility lower bound (the staged-solve gate).

The fused union program (first-fit ∪ best-fit ∪ repair) pays the full
K-step scan for every candidate lane, yet the loop policy drains only the
*first* feasible candidate (reference rescheduler.go:228-287) — most of
that work buys nothing. This module computes, entirely on device and in
O(C·K·R + S·R), a *sound* per-lane verdict: a lane whose **aggregate**
evictable demand exceeds the spot pool's **aggregate** headroom in any
resource dimension — or whose evictable-pod count exceeds the pool's
total free pod slots — can never pack, under any assignment, so the
staged planner (solver/select.py) may skip it without solving it.

Soundness argument (the verdict may only ever say "maybe feasible" for a
feasible lane, never "infeasible"):

- every placement requires per-resource fit on its node, so the demand a
  node can absorb is bounded by ``max(spot_free, 0)`` per resource and
  placements only land on ``spot_ok`` nodes → summed positive headroom
  over ok nodes bounds total placeable demand;
- every placement requires ``count < max_pods`` → a node absorbs at most
  ``max(max_pods - count, 0)`` pods;
- invalid lanes (``cand_valid`` false) are *exactly* infeasible: every
  solver ANDs its feasibility vector with ``cand_valid``.

Float discipline: packed values are integer-valued float32 < 2**24
(models/tensors.py), but device reductions over thousands of spot rows
may round either way. The margin below over-approximates the worst-case
relative error of a naive f32 summation at north-star scale (n·eps/2 ≈
3e-4 at S=50k) by an order of magnitude, so a lane sitting exactly on
the capacity boundary is never eliminated by rounding — it merely gets
solved like before. The filter loses (at most) lanes within ~1% of the
boundary; everything it keeps is decided by the real solver, so the
*selection* is unaffected either way.
"""

from __future__ import annotations

import jax.numpy as jnp

# relative slack covering worst-case naive-f32-summation error (see above)
REL_MARGIN = 1.0 / 128.0


def lane_maybe_feasible(packed):
    """bool [C]: False = lane provably infeasible (skippable); True =
    undecided (must be solved). Jittable over a PackedCluster of host or
    device arrays."""
    valid = jnp.asarray(packed.slot_valid)
    req = jnp.asarray(packed.slot_req) * valid[..., None]
    demand = jnp.sum(req, axis=1)  # f32 [C, R]
    n_slots = jnp.sum(valid, axis=1).astype(jnp.int32)  # [C]

    ok = jnp.asarray(packed.spot_ok)
    headroom = jnp.sum(
        jnp.maximum(jnp.asarray(packed.spot_free), 0.0) * ok[:, None], axis=0
    )  # f32 [R]
    free_slots = jnp.sum(
        jnp.maximum(
            jnp.asarray(packed.spot_max_pods)
            - jnp.asarray(packed.spot_count),
            0,
        )
        * ok,
        axis=0,
    ).astype(jnp.int32)  # scalar

    over_capacity = jnp.any(
        demand > headroom[None, :] * (1.0 + REL_MARGIN) + 1.0, axis=1
    )
    over_slots = n_slots > free_slots  # integer math: exact
    return jnp.asarray(packed.cand_valid) & ~(over_capacity | over_slots)


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr). The jit site lives in solver/select.py
# (StagedPlanner wraps this fn); the root resolves here.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)

HOT_PROGRAMS = {
    "prefilter.lane_bound": HotProgram(
        build=lambda s: (lane_maybe_feasible, (packed_struct(s),)),
        covers=("solver.prefilter:lane_maybe_feasible",),
    ),
}
