"""Batched first-fit drain solver on TPU (JAX).

Replaces the reference's O(candidates × pods × spotNodes) *serial* probe
nest (reference rescheduler.go:334-370, the "HOT LOOP" of SURVEY.md §3.2)
with one compiled program:

- the **candidate axis** is data-parallel: every on-demand node's
  Fork/simulate/Revert (rescheduler.go:269-275) becomes an independent batch
  lane with its own copy of the spot-pool state — lanes never interact,
  matching the reference's one-drain-per-tick semantics where each
  candidate is judged against the same starting snapshot;
- the **pod-slot axis** is the only true sequential dependency (each
  placement depletes capacity for the candidate's later pods,
  rescheduler.go:366), so it is a ``lax.scan`` of length K = max pods per
  candidate — NOT of length total-pods: 50k pods over 5k nodes is a ~K=64
  scan of wide vectorized steps, not a 50k-step loop;
- the **spot axis** is vectorized inside each step: all predicates for all
  (lane, spot) pairs at once, then "first fit in probe order" is an argmax
  over the boolean fit row (argmax returns the first maximum — exactly the
  reference's linear probe order, rescheduler.go:339-350).

Layout: the mutable carries keep the wide spot axis MINOR — [C, R, S] and
[C, A, S] — because TPU tiles the minor dim to 128 lanes; a minor axis of
R=2 would pad 64x in HBM (predicates/masks.fit_mask_t). Capacities are
float32 integers < 2**24 (exact); masks are uint32; shapes are static.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.predicates.masks import fit_mask_t
from k8s_spot_rescheduler_tpu.solver.result import SolveResult


class _Carry(NamedTuple):
    free: jax.Array  # f32 [C, R, S]
    count: jax.Array  # i32 [C, S]
    aff: jax.Array  # u32 [C, A, S]
    feasible: jax.Array  # bool [C]


def _scan_step(static, best_fit, carry: _Carry, slot):
    """Place pod-slot k for every candidate lane at once."""
    spot_max_pods, spot_taints_t, spot_ok = static
    req, valid, tol, aff = slot  # [C,R], [C], [C,W], [C,A]

    fits = fit_mask_t(
        jnp,
        free_t=carry.free,
        count=carry.count,
        max_pods=spot_max_pods,
        node_taints_t=spot_taints_t,
        node_ok=spot_ok,
        node_aff_t=carry.aff,
        req=req,
        tol=tol,
        aff=aff,
    )  # bool [C, S]

    any_fit = jnp.any(fits, axis=-1)
    if best_fit:
        # fallback packing: tightest primary-resource fit, ties → probe
        # order (argmin returns the first minimum)
        slack = jnp.where(fits, carry.free[:, 0, :] - req[:, None, 0], jnp.inf)
        first = jnp.argmin(slack, axis=-1)
    else:
        first = jnp.argmax(fits, axis=-1)  # first fitting spot per lane
    place = valid & any_fit

    S = fits.shape[-1]
    onehot = (jnp.arange(S)[None, :] == first[:, None]) & place[:, None]  # [C,S]

    free = carry.free - onehot[:, None, :] * req[:, :, None]
    count = carry.count + onehot.astype(carry.count.dtype)
    aff_acc = carry.aff | jnp.where(onehot[:, None, :], aff[:, :, None], 0)
    feasible = carry.feasible & (any_fit | ~valid)

    chosen = jnp.where(place, first.astype(jnp.int32), jnp.int32(-1))
    return _Carry(free, count, aff_acc, feasible), chosen


def plan_ffd(packed: PackedCluster, best_fit: bool = False) -> SolveResult:
    """Jittable batched first-fit (or, with ``best_fit``, best-fit
    fallback-mode) solve over a PackedCluster (device arrays)."""
    C = packed.slot_req.shape[0]
    S = packed.spot_free.shape[0]

    free_t = jnp.asarray(packed.spot_free).T  # [R, S]
    aff_t = jnp.asarray(packed.spot_aff).T  # [A, S]
    carry = _Carry(
        free=jnp.broadcast_to(free_t, (C, *free_t.shape)),
        count=jnp.broadcast_to(packed.spot_count, (C, S)).astype(jnp.int32),
        aff=jnp.broadcast_to(aff_t, (C, *aff_t.shape)),
        feasible=jnp.asarray(packed.cand_valid),
    )
    static = (
        jnp.asarray(packed.spot_max_pods),
        jnp.asarray(packed.spot_taints).T,  # [W, S]
        jnp.asarray(packed.spot_ok),
    )

    slots = (
        jnp.moveaxis(packed.slot_req, 1, 0),  # [K, C, R]
        jnp.moveaxis(packed.slot_valid, 1, 0),  # [K, C]
        jnp.moveaxis(packed.slot_tol, 1, 0),  # [K, C, W]
        jnp.moveaxis(packed.slot_aff, 1, 0),  # [K, C, A]
    )

    carry, chosen = jax.lax.scan(
        functools.partial(_scan_step, static, best_fit), carry, slots
    )  # chosen: [K, C]

    feasible = carry.feasible & jnp.asarray(packed.cand_valid)
    # revert semantics (rescheduler.go:273): infeasible lanes report no plan
    assignment = jnp.where(feasible[None, :], chosen, -1).T  # [C, K]
    return SolveResult(feasible=feasible, assignment=assignment)


plan_ffd_jit = jax.jit(plan_ffd, static_argnames=("best_fit",))


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): the traced shapes of this module's jit root.
# manifest-contract (make analyze) fails if the root loses coverage.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)

HOT_PROGRAMS = {
    "ffd.first_fit": HotProgram(
        build=lambda s: (plan_ffd, (packed_struct(s),)),
        covers=("solver.ffd:plan_ffd",),
    ),
    "ffd.best_fit": HotProgram(
        build=lambda s: (
            functools.partial(plan_ffd, best_fit=True),
            (packed_struct(s),),
        ),
        covers=("solver.ffd:plan_ffd",),
    ),
}
