"""Batched first-fit drain solver on TPU (JAX).

Replaces the reference's O(candidates × pods × spotNodes) *serial* probe
nest (reference rescheduler.go:334-370, the "HOT LOOP" of SURVEY.md §3.2)
with one compiled program:

- the **candidate axis** is data-parallel: every on-demand node's
  Fork/simulate/Revert (rescheduler.go:269-275) becomes an independent batch
  lane with its own copy of the spot-pool state — lanes never interact,
  matching the reference's one-drain-per-tick semantics where each
  candidate is judged against the same starting snapshot;
- the **pod-slot axis** is the only true sequential dependency (each
  placement depletes capacity for the candidate's later pods,
  rescheduler.go:366), so it is a ``lax.scan`` of length K = max pods per
  candidate — NOT of length total-pods: 50k pods over 5k nodes is a ~K=64
  scan of wide vectorized steps, not a 50k-step loop;
- the **spot axis** is vectorized inside each step: all predicates for all
  (lane, spot) pairs at once, then "first fit in probe order" is an argmax
  over the boolean fit row (argmax returns the first maximum — exactly the
  reference's linear probe order, rescheduler.go:339-350).

Layout: the mutable carries keep the wide spot axis MINOR — [C, R, S] and
[C, A, S] — because TPU tiles the minor dim to 128 lanes; a minor axis of
R=2 would pad 64x in HBM (predicates/masks.fit_mask_t). Capacities are
float32 integers < 2**24 (exact); masks are uint32; shapes are static.

Carry discipline (ROADMAP 5, the 20x reshape): the scan state is a
DELTA against the static spot rows — capacity *consumed*, placements
*added*, pod-contributed affinity bits — not the absolute free/count/aff
the carries historically held. The statics are read-only scan inputs and
``_widen`` reconstructs the absolute values at ONE site per read, so the
selection arithmetic is bit-identical (exact f32 integers) while the
carried planes can be narrow ints: ``solver/carry.CarryLayout`` sizes
them int16/int8/uint16 from exact host-side pack bounds
(``carry_layout``), cutting the resident per-(lane, spot) carry bytes
~2x and moving the fully-chunked scaling ceiling past 20x.
``plan_ffd_streamed`` additionally streams the spot axis through the
scan in ordered chunks — first-fit decomposes exactly with leftovers
flowing forward (the ops/pallas_ffd ``_plan_ffd_chunked`` property,
lifted to the carry itself), so the first-fit pass's resident carry is
O(S / carry_chunks); best-fit keeps a stacked narrow state with the
per-slot elect-then-commit election proven for the chunked repair's
partial pass.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.predicates.masks import fit_mask_t
from k8s_spot_rescheduler_tpu.solver.carry import (
    CarryLayout,
    NARROW_LAYOUT,
    WIDE_LAYOUT,
)
# re-exported: the kernel-facing layout surface (tests and the planner
# import carry_layout from here beside plan_ffd)
from k8s_spot_rescheduler_tpu.solver.carry import carry_layout  # noqa: F401
from k8s_spot_rescheduler_tpu.solver.result import SolveResult

__all__ = [
    "CarryLayout",
    "NARROW_LAYOUT",
    "WIDE_LAYOUT",
    "carry_layout",
    "plan_ffd",
    "plan_ffd_jit",
    "plan_ffd_streamed",
    "plan_ffd_streamed_jit",
]


class _SpotStatics(NamedTuple):
    """The read-only spot rows the delta carries widen against (full
    axis in ``plan_ffd``; one chunk's slice in the streamed kernels)."""

    free_t: jax.Array  # f32 [R, S]
    count: jax.Array  # i32 [S]
    aff_t: jax.Array  # u32 [A, S]
    max_pods: jax.Array  # i32 [S]
    taints_t: jax.Array  # u32 [W, S]
    ok: jax.Array  # bool [S]


class _Carry(NamedTuple):
    """Delta-form mutable state (dtypes from a CarryLayout)."""

    used: jax.Array  # layout.used [C, R, S] — capacity consumed
    dcount: jax.Array  # layout.count [C, S] — placements added
    daff: jax.Array  # layout.aff [C, A, S] — placed pods' aff bits
    feasible: jax.Array  # bool [C]


def _widen(static: _SpotStatics, used, dcount, daff):
    """THE widen-on-read site: absolute (free_t, count, aff_t) views of
    a delta carry. Exact — consumed/placed values are integers within
    the layout guard's bounds, so the casts lose nothing and the
    arithmetic downstream is bit-identical to the wide layout."""
    free_t = static.free_t - used.astype(static.free_t.dtype)
    count = static.count + dcount.astype(static.count.dtype)
    aff_t = static.aff_t | daff.astype(static.aff_t.dtype)
    return free_t, count, aff_t


def _zero_carry(
    layout: CarryLayout, C: int, R: int, A: int, S: int, feasible
) -> _Carry:
    return _Carry(
        used=jnp.zeros((C, R, S), layout.used),
        dcount=jnp.zeros((C, S), layout.count),
        daff=jnp.zeros((C, A, S), layout.aff),
        feasible=feasible,
    )


def _spot_statics(packed: PackedCluster) -> _SpotStatics:
    return _SpotStatics(
        free_t=jnp.asarray(packed.spot_free).T,  # [R, S]
        count=jnp.asarray(packed.spot_count).astype(jnp.int32),
        aff_t=jnp.asarray(packed.spot_aff).T,  # [A, S]
        max_pods=jnp.asarray(packed.spot_max_pods),
        taints_t=jnp.asarray(packed.spot_taints).T,  # [W, S]
        ok=jnp.asarray(packed.spot_ok),
    )


def _slot_stream(packed: PackedCluster):
    return (
        jnp.moveaxis(jnp.asarray(packed.slot_req), 1, 0),  # [K, C, R]
        jnp.moveaxis(jnp.asarray(packed.slot_valid), 1, 0),  # [K, C]
        jnp.moveaxis(jnp.asarray(packed.slot_tol), 1, 0),  # [K, C, W]
        jnp.moveaxis(jnp.asarray(packed.slot_aff), 1, 0),  # [K, C, A]
    )


def _scan_step(static: _SpotStatics, best_fit, carry: _Carry, slot):
    """Place pod-slot k for every candidate lane at once."""
    req, valid, tol, aff = slot  # [C,R], [C], [C,W], [C,A]
    free_t, count, aff_t = _widen(
        static, carry.used, carry.dcount, carry.daff
    )

    fits = fit_mask_t(
        jnp,
        free_t=free_t,
        count=count,
        max_pods=static.max_pods,
        node_taints_t=static.taints_t,
        node_ok=static.ok,
        node_aff_t=aff_t,
        req=req,
        tol=tol,
        aff=aff,
    )  # bool [C, S]

    any_fit = jnp.any(fits, axis=-1)
    if best_fit:
        # fallback packing: tightest primary-resource fit, ties → probe
        # order (argmin returns the first minimum)
        slack = jnp.where(fits, free_t[:, 0, :] - req[:, None, 0], jnp.inf)
        first = jnp.argmin(slack, axis=-1)
    else:
        first = jnp.argmax(fits, axis=-1)  # first fitting spot per lane
    place = valid & any_fit

    S = fits.shape[-1]
    onehot = (jnp.arange(S)[None, :] == first[:, None]) & place[:, None]  # [C,S]

    used = carry.used + (
        onehot[:, None, :] * req[:, :, None]
    ).astype(carry.used.dtype)
    dcount = carry.dcount + onehot.astype(carry.dcount.dtype)
    daff = carry.daff | jnp.where(
        onehot[:, None, :], aff[:, :, None], 0
    ).astype(carry.daff.dtype)
    feasible = carry.feasible & (any_fit | ~valid)

    chosen = jnp.where(place, first.astype(jnp.int32), jnp.int32(-1))
    return _Carry(used, dcount, daff, feasible), chosen


def plan_ffd(
    packed: PackedCluster,
    best_fit: bool = False,
    layout: CarryLayout = WIDE_LAYOUT,
) -> SolveResult:
    """Jittable batched first-fit (or, with ``best_fit``, best-fit
    fallback-mode) solve over a PackedCluster (device arrays).
    ``layout`` narrows the delta carries (solver/carry.py); the caller
    must only pass a narrow layout ``carry_layout(packed)`` proves —
    the default wide layout is always exact."""
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    A = packed.spot_aff.shape[1]

    static = _spot_statics(packed)
    carry = _zero_carry(
        layout, C, R, A, S, jnp.asarray(packed.cand_valid)
    )
    carry, chosen = jax.lax.scan(
        functools.partial(_scan_step, static, best_fit),
        carry,
        _slot_stream(packed),
    )  # chosen: [K, C]

    feasible = carry.feasible & jnp.asarray(packed.cand_valid)
    # revert semantics (rescheduler.go:273): infeasible lanes report no plan
    assignment = jnp.where(feasible[None, :], chosen, -1).T  # [C, K]
    return SolveResult(feasible=feasible, assignment=assignment)


plan_ffd_jit = jax.jit(plan_ffd, static_argnames=("best_fit", "layout"))


# --- spot-streamed kernels (ROADMAP 5) -------------------------------------

def chunk_minor(arr, n: int, Sc: int):
    """[..., n*Sc] -> [n, ..., Sc]: split the minor spot axis into n
    ordered chunk-major blocks (block j holds global spots
    [j*Sc, (j+1)*Sc))."""
    parts = jnp.reshape(arr, (*arr.shape[:-1], n, Sc))
    return jnp.moveaxis(parts, -2, 0)


def pad_spot_axis(arr, pad: int):
    """Pad the leading spot axis with ``pad`` inert rows (the padded
    nodes carry spot_ok=False and sit at the END of the probe order, so
    placements and global indices are unchanged)."""
    arr = jnp.asarray(arr)
    if pad == 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return jnp.pad(arr, widths)


def chunked_spot_statics(packed: PackedCluster, n: int, Sc: int):
    """The spot statics split into n ordered chunks:
    (free0 [n,R,Sc], count0 [n,Sc], aff0 [n,A,Sc], taints [n,W,Sc],
    ok [n,Sc], max_pods [n,Sc], offs [n])."""
    S = packed.spot_free.shape[0]
    pad = n * Sc - S
    return (
        chunk_minor(pad_spot_axis(packed.spot_free, pad).T, n, Sc),
        chunk_minor(
            pad_spot_axis(packed.spot_count, pad).astype(jnp.int32), n, Sc
        ),
        chunk_minor(pad_spot_axis(packed.spot_aff, pad).T, n, Sc),
        chunk_minor(pad_spot_axis(packed.spot_taints, pad).T, n, Sc),
        chunk_minor(pad_spot_axis(packed.spot_ok, pad), n, Sc),
        chunk_minor(pad_spot_axis(packed.spot_max_pods, pad), n, Sc),
        jnp.arange(n, dtype=jnp.int32) * Sc,
    )


def _zero_chunk_state(layout: CarryLayout, n, C, R, A, Sc):
    """Stacked delta state over n chunks (best-fit / repair rounds)."""
    return (
        jnp.zeros((n, C, R, Sc), layout.used),
        jnp.zeros((n, C, Sc), layout.count),
        jnp.zeros((n, C, A, Sc), layout.aff),
    )


def _widen_chunk(free0, count0, aff0, used, dcount, daff):
    """Per-chunk twin of ``_widen`` (chunk statics vs chunk deltas)."""
    return (
        free0 - used.astype(free0.dtype),
        count0 + dcount.astype(count0.dtype),
        aff0 | daff.astype(aff0.dtype),
    )


def _stream_bf_step(chunk_xs, Sc, state, slot):
    """One best-fit placement across ordered spot chunks, delta-form:
    each chunk elects its local tightest fit; a lexicographic
    (slack, chunk-order) election picks the global winner — identical
    to the unchunked argmin (ties resolve to the earlier probe index) —
    and only the winning chunk's state commits. Returns
    (state, (chosen global index or -1, any_fit))."""
    free0_c, count0_c, aff0_c, taints_c, ok_c, maxp_c, offs = chunk_xs
    used_c, dcount_c, daff_c = state
    req, valid, tol, aff = slot  # [C,R], [C], [C,W], [C,A]
    C = req.shape[0]

    def elect(best, xs):
        best_slack, best_g = best
        (used_j, dcount_j, daff_j, free0_j, count0_j, aff0_j,
         taints_j, ok_j, maxp_j, off) = xs
        free_j, count_j, aff_j = _widen_chunk(
            free0_j, count0_j, aff0_j, used_j, dcount_j, daff_j
        )
        fits = fit_mask_t(
            jnp,
            free_t=free_j,
            count=count_j,
            max_pods=maxp_j,
            node_taints_t=taints_j,
            node_ok=ok_j,
            node_aff_t=aff_j,
            req=req,
            tol=tol,
            aff=aff,
        )  # [C, Sc]
        slack = jnp.where(fits, free_j[:, 0, :] - req[:, None, 0], jnp.inf)
        m = jnp.min(slack, axis=-1)
        i = jnp.argmin(slack, axis=-1).astype(jnp.int32)
        better = m < best_slack  # strict: ties keep the earlier chunk
        return (
            jnp.where(better, m, best_slack),
            jnp.where(better, off + i, best_g),
        ), None

    (best_slack, best_g), _ = jax.lax.scan(
        elect,
        (
            jnp.full((C,), jnp.inf, free0_c.dtype),
            jnp.zeros((C,), jnp.int32),
        ),
        (used_c, dcount_c, daff_c, *chunk_xs),
    )
    any_fit = jnp.isfinite(best_slack)
    place = valid & any_fit

    def commit(xs):
        used_j, dcount_j, daff_j, off = xs
        loc = best_g - off
        onehot = (
            jnp.arange(Sc)[None, :] == loc[:, None]
        ) & place[:, None]  # [C, Sc]
        return (
            used_j + (
                onehot[:, None, :] * req[:, :, None]
            ).astype(used_j.dtype),
            dcount_j + onehot.astype(dcount_j.dtype),
            daff_j | jnp.where(
                onehot[:, None, :], aff[:, :, None], 0
            ).astype(daff_j.dtype),
        )

    used_c, dcount_c, daff_c = jax.lax.map(
        commit, (used_c, dcount_c, daff_c, offs)
    )
    chosen = jnp.where(place, best_g, jnp.int32(-1))
    return (used_c, dcount_c, daff_c), (chosen, any_fit)


def plan_ffd_streamed(
    packed: PackedCluster,
    *,
    carry_chunks: int = 2,
    layout: CarryLayout = WIDE_LAYOUT,
    best_fit: bool = False,
) -> SolveResult:
    """``plan_ffd`` with the spot axis streamed through the scan in
    ``carry_chunks`` ordered chunks.

    First-fit decomposes EXACTLY over an ordered spot partition with
    leftover pods flowing forward (per-spot state is chunk-independent
    and first-fit prefers earlier spots — the ops/pallas_ffd
    ``_plan_ffd_chunked`` property): each chunk runs the full K-slot
    scan against its own chunk-local delta carry (zeros-initialized —
    the statics are scan inputs), placing every still-unplaced pod that
    fits, so the RESIDENT first-fit carry is O(S / carry_chunks) and
    the cross-chunk carry is just the O(C·K) remaining/chosen bookkeep.

    Best-fit's global tightest-slack election does not stream; with
    ``best_fit`` the kernel runs the per-slot elect-then-commit over a
    STACKED narrow chunk state (``_stream_bf_step``) — same results as
    ``plan_ffd(best_fit=True)``, resident carry narrow but O(S).

    Bit-identical to ``plan_ffd`` in both modes (pinned by
    tests/test_carry_stream.py at multiple chunk counts); the spot axis
    is padded to a chunk multiple with inert nodes at the end of the
    probe order, so placements and assignment indices are unchanged."""
    if carry_chunks <= 1:
        return plan_ffd(packed, best_fit=best_fit, layout=layout)
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    A = packed.spot_aff.shape[1]
    n = int(carry_chunks)
    Sc = -(-S // n)
    chunk_xs = chunked_spot_statics(packed, n, Sc)
    slots = _slot_stream(packed)

    if best_fit:
        def bf_step(carry, slot):
            state, feasible = carry
            _, valid, _, _ = slot
            state, (chosen, any_fit) = _stream_bf_step(
                chunk_xs, Sc, state, slot
            )
            return (state, feasible & (any_fit | ~valid)), chosen

        (_, feasible), chosen = jax.lax.scan(
            bf_step,
            (
                _zero_chunk_state(layout, n, C, R, A, Sc),
                jnp.asarray(packed.cand_valid),
            ),
            slots,
        )
        feasible = feasible & jnp.asarray(packed.cand_valid)
        assignment = jnp.where(feasible[None, :], chosen, -1).T
        return SolveResult(feasible=feasible, assignment=assignment)

    slot_req_k, _, slot_tol_k, slot_aff_k = slots

    def chunk_step(carry, xs):
        remaining, chosen = carry  # [C, K] bool, [C, K] i32
        free0_j, count0_j, aff0_j, taints_j, ok_j, maxp_j, off = xs
        static_j = _SpotStatics(
            free_t=free0_j,
            count=count0_j,
            aff_t=aff0_j,
            max_pods=maxp_j,
            taints_t=taints_j,
            ok=ok_j,
        )
        inner = _zero_carry(
            layout, C, R, A, Sc, jnp.ones((C,), bool)
        )

        def slot_step(c, slot_k):
            # feasibility is the outer loop's job (a leftover pod may
            # still place in a later chunk); keep the inner flag inert
            new_c, chosen_local = _scan_step(static_j, False, c, slot_k)
            return new_c._replace(feasible=c.feasible), chosen_local

        _, chosen_local = jax.lax.scan(
            slot_step,
            inner,
            (
                slot_req_k,
                jnp.moveaxis(remaining, 1, 0),  # [K, C]
                slot_tol_k,
                slot_aff_k,
            ),
        )  # chosen_local: [K, C], -1 = no fit in this chunk
        placed = (chosen_local >= 0).T  # [C, K]
        chosen = jnp.where(placed, chosen_local.T + off, chosen)
        remaining = remaining & ~placed
        return (remaining, chosen), None

    (remaining, chosen), _ = jax.lax.scan(
        chunk_step,
        (
            jnp.asarray(packed.slot_valid),
            jnp.full((C, K), -1, jnp.int32),
        ),
        chunk_xs,
    )
    # a lane is feasible iff nothing valid remains unplaced — identical
    # to plan_ffd's per-turn verdict (a pod with no fit anywhere at its
    # turn can never place later: chunk states at its turn are exactly
    # the global first-fit states)
    feasible = jnp.asarray(packed.cand_valid) & ~jnp.any(remaining, axis=1)
    assignment = jnp.where(feasible[:, None], chosen, -1)
    return SolveResult(feasible=feasible, assignment=assignment)


plan_ffd_streamed_jit = jax.jit(
    plan_ffd_streamed,
    static_argnames=("carry_chunks", "layout", "best_fit"),
)


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): the traced shapes of this module's jit roots.
# manifest-contract (make analyze) fails if a root loses coverage. The
# streamed variants trace at the NARROW layout — the dtype pass then
# sees the exact int16/int8/uint16 carry program the 20x tier runs.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)

HOT_PROGRAMS = {
    "ffd.first_fit": HotProgram(
        build=lambda s: (plan_ffd, (packed_struct(s),)),
        covers=("solver.ffd:plan_ffd",),
    ),
    "ffd.best_fit": HotProgram(
        build=lambda s: (
            functools.partial(plan_ffd, best_fit=True),
            (packed_struct(s),),
        ),
        covers=("solver.ffd:plan_ffd",),
    ),
    "ffd.streamed": HotProgram(
        build=lambda s: (
            functools.partial(
                plan_ffd_streamed, carry_chunks=4, layout=NARROW_LAYOUT
            ),
            (packed_struct(s),),
        ),
        covers=("solver.ffd:plan_ffd_streamed",),
    ),
    "ffd.streamed_best_fit": HotProgram(
        build=lambda s: (
            functools.partial(
                plan_ffd_streamed,
                carry_chunks=4,
                layout=NARROW_LAYOUT,
                best_fit=True,
            ),
            (packed_struct(s),),
        ),
        covers=("solver.ffd:plan_ffd_streamed",),
    ),
}
