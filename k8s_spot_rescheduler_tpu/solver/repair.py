"""Bounded eject-and-reinsert local search — the "+ local-search" half
of the north-star kernel (SURVEY.md §7 step 5, BASELINE.md).

Greedy packing (first-fit or best-fit decreasing) fails a candidate lane
the moment one pod fits nowhere, even when relocating a single
already-placed pod would unlock it — the regime where the reference's
serial probe nest (reference rescheduler.go:334-370) and any one-pass
heuristic lose drains at high spot utilization. This module recovers
those lanes:

1. **Partial pass** — the best-fit-decreasing scan of solver/ffd.py but
   *continue on failure*: place every pod that fits, leave gaps
   (``assignment == -1``) instead of aborting the lane.
2. **Repair rounds** — a fixed-length ``lax.scan``; each round, every
   unfinished lane in parallel picks its first unplaced pod ``p``,
   searches the already-placed pods ``q`` whose ejection would let
   ``p`` take their node, rotates deterministically through those
   unlockers across rounds, and executes the relocation
   ``q → elsewhere, p → q's node`` when ``q`` itself re-places. When
   ``q`` CANNOT re-place directly, a depth-2 CHAIN (round 4) relocates
   it onto a third pod ``r``'s node and re-places ``r`` elsewhere
   (``p → s_q, q → s_r, r → s3``) — closing the two-pod interlock that
   defeated depth-1 (the published boundary moves to three-link
   chains, docs/RESULTS.md).
3. **Validation** — the final assignment is re-proven from scratch
   (solver/validate.py) on device; only fully-placed, predicate-valid
   lanes report feasible. The search can therefore never approve an
   invalid drain, no matter what (hard part (e): conservative only).

TPU shape discipline matches solver/ffd.py: carries keep the spot axis
minor ([C, R, S] / [C, A, S]), shapes are static, rounds are a scan.
Since the ROADMAP-5 reshape the carried state is DELTA-form against the
static spot rows (capacity consumed / placements added / pod-contributed
affinity bits — solver/carry.CarryLayout sizes the dtypes, int16/int8/
uint16 when the pack's exact host-side bounds fit), widened on read at
the shared ``solver/ffd._widen`` site so every election and gate below
computes on bit-identical absolute values.

Affinity ejection is EXACT (round 4; was monotone-conservative before):
the per-node affinity state starts exact after the partial pass (static
resident bits OR placed pods' bits — no ejections yet) and every
relocation recomputes the ejected node's word from scratch (static bits
OR the bits of pods still assigned there), so ejecting ``q`` genuinely
clears its group bits and affinity-driven unlocks — a group member
vacating the node its group-mate needs — are found. The unlock
*election* stays cheap (resources + static words only); the elected
move is gated by the exact recompute, and the deterministic rotation
tries a different unlocker next round when the gate fails. Every final
assignment is still re-proven from scratch, so no exactness bug can
ever approve an invalid drain.

Cost: each round is O(K·(R+A) + S·(R+W)) per lane vs the greedy scan's
O(K·S·(R+W)) — ``ROUNDS`` rounds add well under 2x total solve time.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.predicates.masks import fit_mask, fit_mask_t
from k8s_spot_rescheduler_tpu.solver.carry import CarryLayout, WIDE_LAYOUT
from k8s_spot_rescheduler_tpu.solver.ffd import (
    _Carry,
    _scan_step,
    _spot_statics,
    _slot_stream,
    _stream_bf_step,
    _widen,
    _widen_chunk,
    _zero_carry,
    _zero_chunk_state,
    chunk_minor,
    chunked_spot_statics,
    pad_spot_axis,
)
from k8s_spot_rescheduler_tpu.solver.result import SolveResult
from k8s_spot_rescheduler_tpu.solver.validate import validate_assignment

DEFAULT_ROUNDS = 8

# kept as the chunk-splitting helper's historical name for callers
_chunk_minor = chunk_minor


class _RepairCarry(NamedTuple):
    """Delta-form repair state (dtypes from a CarryLayout); the absolute
    free/count/aff views are rebuilt per round at the one widen site."""

    used: jax.Array  # layout.used [C, R, S]
    dcount: jax.Array  # layout.count [C, S]
    daff: jax.Array  # layout.aff [C, A, S]
    assign: jax.Array  # i32 [C, K]


def _partial_scan_step(static, carry: _Carry, slot):
    """solver/ffd.py ``_scan_step`` in best-fit mode, but a pod that fits
    nowhere leaves a gap instead of failing the lane."""
    new_carry, chosen = _scan_step(static, True, carry, slot)
    # keep scanning: feasibility tracking is repair's job now
    return new_carry._replace(feasible=carry.feasible), chosen


def _repair_round(static, chain, state: _RepairCarry, round_idx):
    """``chain`` (compile-time bool) gates the depth-2 block — the
    chain-depth-demand analyzer (bench/chain_depth.py) compiles a
    depth-1-only variant to classify which lanes genuinely NEED the
    chain; production always passes True."""
    (spot_static, spot_aff_static,
     slot_req, slot_valid, slot_tol, slot_aff) = static
    spot_max_pods = spot_static.max_pods
    spot_taints_t = spot_static.taints_t
    spot_ok = spot_static.ok
    C, K, R = slot_req.shape
    S = state.used.shape[-1]
    # the one widen-on-read: every election and gate below sees the
    # absolute values the wide layout carried
    free, count, aff = _widen(
        spot_static, state.used, state.dcount, state.daff
    )

    unplaced = slot_valid & (state.assign < 0)  # [C, K]
    has_gap = jnp.any(unplaced, axis=-1)  # [C]
    p = jnp.argmax(unplaced, axis=-1)  # first unplaced slot per lane

    req_p = jnp.take_along_axis(slot_req, p[:, None, None], axis=1)[:, 0]
    tol_p = jnp.take_along_axis(slot_tol, p[:, None, None], axis=1)[:, 0]
    aff_p = jnp.take_along_axis(slot_aff, p[:, None, None], axis=1)[:, 0]

    # static admission of p per spot node (taints/selector words + ok)
    word_ok = jnp.all(
        (spot_taints_t & ~tol_p[:, :, None]) == 0, axis=1
    )  # [C, S]
    static_p = word_ok & spot_ok  # [C, S]

    placed = state.assign >= 0  # [C, K]
    s_q = jnp.clip(state.assign, 0, S - 1)  # [C, K]

    # would p fit on q's node if q were ejected? (resources + static
    # words; the affinity gate is exact and applied to the ELECTED
    # unlocker below — a per-candidate exact recompute here would cost
    # O(K^2·A) for nothing, since rotation retries next round anyway)
    free_at_q = jnp.take_along_axis(
        free, s_q[:, None, :], axis=2
    )  # [C, R, K]
    req_t = jnp.swapaxes(slot_req, 1, 2)  # [C, R, K]
    res_ok = jnp.all(
        free_at_q + req_t - req_p[:, :, None] >= 0, axis=1
    )  # [C, K]
    static_at_q = jnp.take_along_axis(static_p, s_q, axis=1)  # [C, K]

    unlock = placed & res_ok & static_at_q  # [C, K]
    n_unlock = unlock.sum(axis=-1)  # [C]

    # deterministic rotation: try a different unlocker each round
    rank = jnp.cumsum(unlock, axis=-1) - 1
    want = jnp.where(
        n_unlock > 0, round_idx % jnp.maximum(n_unlock, 1), -1
    )
    is_q = unlock & (rank == want[:, None])
    q = jnp.argmax(is_q, axis=-1)  # [C]
    any_q = jnp.any(is_q, axis=-1)

    # can q itself re-place somewhere else under current state?
    req_q = jnp.take_along_axis(slot_req, q[:, None, None], axis=1)[:, 0]
    tol_q = jnp.take_along_axis(slot_tol, q[:, None, None], axis=1)[:, 0]
    aff_q = jnp.take_along_axis(slot_aff, q[:, None, None], axis=1)[:, 0]
    sq_star = jnp.take_along_axis(s_q, q[:, None], axis=1)[:, 0]  # [C]

    fits_q = fit_mask_t(
        jnp,
        free_t=free,
        count=count,
        max_pods=spot_max_pods,
        node_taints_t=spot_taints_t,
        node_ok=spot_ok,
        node_aff_t=aff,
        req=req_q,
        tol=tol_q,
        aff=aff_q,
    )  # [C, S]
    fits_q &= jnp.arange(S)[None, :] != sq_star[:, None]
    s2 = jnp.argmax(fits_q, axis=-1)  # [C]
    can_move = jnp.any(fits_q, axis=-1)

    # exact affinity of q's node AFTER q leaves: static resident bits OR
    # the bits of pods still assigned there — ejection genuinely clears
    # q's contribution (a group member vacating for its group-mate).
    # ``aff_ejd`` is the pod-contributed half alone: the WRITE value of
    # the delta carry (the read site ORs the static bits back in).
    ks = jnp.arange(K)[None, :]
    others = placed & (state.assign == sq_star[:, None]) & (ks != q[:, None])
    contrib = jnp.where(
        others[:, None, :], jnp.swapaxes(slot_aff, 1, 2), jnp.uint32(0)
    )  # [C, A, K]
    aff_ejd = jax.lax.reduce(
        contrib, np.uint32(0), jax.lax.bitwise_or, (2,)
    )  # [C, A] — pods-only
    aff_ej = aff_ejd | spot_aff_static[sq_star]  # [C, A] — exact gate value
    aff_ok_p = jnp.all((aff_p & aff_ej) == 0, axis=1)  # [C]

    do_direct = has_gap & any_q & can_move & aff_ok_p  # [C]

    if not chain:
        # depth-1-only variant: no chain block compiles at all; the
        # masked arithmetic below folds to the direct move
        do_chain = jnp.zeros_like(do_direct)
        sr_star = s2
        s3 = s2
        req_r = req_q
        aff_r = aff_q
        aff_ejd_r = aff_ejd
        r = q

    # ---- depth-2 chain (round 4): when q cannot re-place DIRECTLY,
    # relocate it onto a third pod r's node and re-place r elsewhere
    # (p -> s_q, q -> s_r, r -> s3) — the two-pod interlock that
    # defeated depth-1 (docs/RESULTS.md boundary). r is elected by the
    # same rotation; its own re-placement and both exact affinity gates
    # are verified post-election, with rotation retrying on failure.
    if chain:
        word_ok_q = jnp.all(
            (spot_taints_t & ~tol_q[:, :, None]) == 0, axis=1
        )  # [C, S]
        static_q = word_ok_q & spot_ok
        static_q_at = jnp.take_along_axis(static_q, s_q, axis=1)  # [C, K]
        res_ok_r = jnp.all(
            free_at_q + req_t - req_q[:, :, None] >= 0, axis=1
        )  # [C, K] — q fits r's node once r is ejected
        eligible_r = (
            placed & (s_q != sq_star[:, None]) & static_q_at & res_ok_r
        )  # [C, K]
        n_r = eligible_r.sum(axis=-1)
        rank_r = jnp.cumsum(eligible_r, axis=-1) - 1
        # r rotates on an INDEPENDENT schedule (divided by the q-rotation
        # period): keying both to round_idx would lock the pairings to
        # q ≡ r (mod gcd(n_unlock, n_r)) and leave whole (q, r) pairs
        # unreachable at any round count (round-4 review finding); this way
        # n_unlock x n_r rounds sweep every pairing
        want_r = jnp.where(
            n_r > 0,
            (round_idx // jnp.maximum(n_unlock, 1)) % jnp.maximum(n_r, 1),
            -1,
        )
        is_r = eligible_r & (rank_r == want_r[:, None])
        r = jnp.argmax(is_r, axis=-1)  # [C]
        any_r = jnp.any(is_r, axis=-1)
        sr_star = jnp.take_along_axis(s_q, r[:, None], axis=1)[:, 0]  # [C]
        req_r = jnp.take_along_axis(slot_req, r[:, None, None], axis=1)[:, 0]
        tol_r = jnp.take_along_axis(slot_tol, r[:, None, None], axis=1)[:, 0]
        aff_r = jnp.take_along_axis(slot_aff, r[:, None, None], axis=1)[:, 0]

        fits_r = fit_mask_t(
            jnp,
            free_t=free,
            count=count,
            max_pods=spot_max_pods,
            node_taints_t=spot_taints_t,
            node_ok=spot_ok,
            node_aff_t=aff,
            req=req_r,
            tol=tol_r,
            aff=aff_r,
        )  # [C, S]
        fits_r &= (jnp.arange(S)[None, :] != sr_star[:, None]) & (
            jnp.arange(S)[None, :] != sq_star[:, None]
        )
        s3 = jnp.argmax(fits_r, axis=-1)  # [C]
        r_can_move = jnp.any(fits_r, axis=-1)

        # exact affinity of r's node after r leaves, for q's arrival
        others_r = placed & (state.assign == sr_star[:, None]) & (
            ks != r[:, None]
        )
        contrib_r = jnp.where(
            others_r[:, None, :], jnp.swapaxes(slot_aff, 1, 2), jnp.uint32(0)
        )
        aff_ejd_r = jax.lax.reduce(
            contrib_r, np.uint32(0), jax.lax.bitwise_or, (2,)
        )  # [C, A] — pods-only
        aff_ej_r = aff_ejd_r | spot_aff_static[sr_star]  # [C, A]
        aff_ok_q = jnp.all((aff_q & aff_ej_r) == 0, axis=1)  # [C]

        do_chain = (
            has_gap & any_q & ~can_move & aff_ok_p
            & any_r & r_can_move & aff_ok_q
        )
    do = do_direct | do_chain  # [C]

    # q's destination: s2 (direct) or r's node (chain); the +1 pod count
    # lands on s2 (direct) or s3 (chain) — every other count nets zero
    q_dest = jnp.where(do_chain, sr_star, s2)
    inc_node = jnp.where(do_chain, s3, s2)
    onehot_sq = jnp.arange(S)[None, :] == sq_star[:, None]  # [C, S]
    onehot_qd = jnp.arange(S)[None, :] == q_dest[:, None]
    onehot_s3 = (jnp.arange(S)[None, :] == s3[:, None]) & do_chain[:, None]
    onehot_inc = jnp.arange(S)[None, :] == inc_node[:, None]
    delta = (
        onehot_sq[:, None, :] * (req_q - req_p)[:, :, None]
        - onehot_qd[:, None, :] * req_q[:, :, None]
        + onehot_qd[:, None, :] * do_chain[:, None, None] * req_r[:, :, None]
        - onehot_s3[:, None, :] * req_r[:, :, None]
    )
    # free += delta  ⇔  used -= delta (delta-form). Widen -> compute ->
    # narrow: the result is invariantly in the layout guard's bounds,
    # but the intermediate ``-delta`` may be negative, which an unsigned
    # narrow dtype must never see.
    used = jnp.where(
        do[:, None, None],
        (state.used.astype(delta.dtype) - delta).astype(state.used.dtype),
        state.used,
    )
    dcount = jnp.where(
        do[:, None],
        state.dcount + onehot_inc.astype(state.dcount.dtype),
        state.dcount,
    )
    # s_q's column is REPLACED by the exact recompute (plus p's
    # arrival); q's destination is replaced on a chain (aff_ejd_r | q's
    # bits) or OR'd on a direct move; s3 accumulates r's bits. All
    # written values are pod-contributed bits only — the widen site ORs
    # the static resident bits back, reproducing the wide layout's
    # absolute columns bit for bit.
    dt = state.daff.dtype
    zero = jnp.zeros((), dt)
    qd_col = jnp.where(
        do_chain[:, None], aff_ejd_r | aff_q, jnp.uint32(0)
    ).astype(dt)  # chain: exact replacement value for s_r
    daff_after = jnp.where(
        onehot_sq[:, None, :],
        (aff_ejd | aff_p).astype(dt)[:, :, None],
        state.daff,
    )
    daff_after = jnp.where(
        (onehot_qd & do_chain[:, None])[:, None, :],
        qd_col[:, :, None],
        daff_after,
    ) | jnp.where(
        (onehot_qd & do_direct[:, None])[:, None, :],
        aff_q.astype(dt)[:, :, None],
        zero,
    ) | jnp.where(
        onehot_s3[:, None, :], aff_r.astype(dt)[:, :, None], zero
    )
    daff = jnp.where(do[:, None, None], daff_after, state.daff)
    assign = jnp.where(
        do[:, None],
        jnp.where(
            ks == p[:, None],
            sq_star[:, None].astype(state.assign.dtype),
            jnp.where(
                ks == q[:, None], q_dest[:, None].astype(state.assign.dtype),
                jnp.where(
                    (ks == r[:, None]) & do_chain[:, None],
                    s3[:, None].astype(state.assign.dtype),
                    state.assign,
                ),
            ),
        ),
        state.assign,
    )
    return _RepairCarry(used, dcount, daff, assign), ()


def plan_repair(
    packed: PackedCluster,
    rounds: int = DEFAULT_ROUNDS,
    chain: bool = True,
    layout: CarryLayout = WIDE_LAYOUT,
) -> SolveResult:
    """Jittable partial-pack + bounded repair + from-scratch validation.
    ``chain=False`` compiles the depth-1-only search — used solely by
    the chain-depth-demand analyzer (bench/chain_depth.py). ``layout``
    narrows the delta carries (callers pass only what
    ``solver/carry.carry_layout`` proves the pack fits)."""
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    A = packed.spot_aff.shape[1]

    static = _spot_statics(packed)
    carry = _zero_carry(
        layout, C, R, A, S, jnp.asarray(packed.cand_valid)
    )
    carry, chosen = jax.lax.scan(
        functools.partial(_partial_scan_step, static),
        carry,
        _slot_stream(packed),
    )
    assign0 = jnp.swapaxes(chosen, 0, 1).astype(jnp.int32)  # [C, K]

    state = _RepairCarry(
        used=carry.used, dcount=carry.dcount, daff=carry.daff,
        assign=assign0,
    )
    repair_static = (
        static,
        jnp.asarray(packed.spot_aff),  # static resident bits, [S, A]
        jnp.asarray(packed.slot_req),
        jnp.asarray(packed.slot_valid),
        jnp.asarray(packed.slot_tol),
        jnp.asarray(packed.slot_aff),
    )
    state, _ = jax.lax.scan(
        functools.partial(_repair_round, repair_static, chain),
        state,
        jnp.arange(rounds),
    )

    feasible = validate_assignment(jnp, packed, state.assign)
    assignment = jnp.where(feasible[:, None], state.assign, -1)
    return SolveResult(feasible=feasible, assignment=assignment)


plan_repair_jit = jax.jit(
    plan_repair, static_argnames=("rounds", "chain", "layout")
)


# --- spot-chunked repair (elect-then-commit) -------------------------------
#
# Past the cand-only sharding tier's unchunked ceiling, one lane block's
# repair program no longer fits a device: the round's working set — the
# unlocker probe, the two first-fit re-placement sweeps, the [C, R, S]
# commit delta and the affinity rewrites — is O(S) wide. First-fit
# already decomposes exactly over an ordered spot partition
# (ops/pallas_ffd._plan_ffd_chunked); the functions below extend that
# decomposition to the eject-and-reinsert search in a two-phase
# *elect-then-commit* form:
#
# 1. ELECT — each spot chunk computes its local unlocker candidates and
#    first-fit re-placement targets; cheap elections combine them:
#    unlockers are a disjoint union over chunks (each placed pod lives
#    in exactly one chunk), and "first fitting node" is the minimum of
#    the chunk-local winners' GLOBAL indices — chunks are ordered, so
#    the minimum reproduces the unchunked argmax-of-bool probe order
#    bit for bit. The q/r rotation then runs on the combined masks in
#    global slot order, unchanged.
# 2. COMMIT — the exact affinity-recompute gate (O(K·A), chunk-free)
#    vets the elected move, and only the chunks holding the (at most
#    three) touched nodes change state.
#
# Per-round temporaries are therefore O(C × S/chunks), never O(C × S),
# and the carried state is the DELTA-form free/count/aff set every
# greedy pass already holds — narrow ints under a CarryLayout, which is
# what moves the fully-chunked ceiling past the old greedy carry bound.
# The final from-scratch validation (solver/validate.py) is unchanged,
# so chunked repair can still never approve an invalid drain. Bit parity
# with ``plan_repair_oracle`` is pinned by tests/test_repair_chunked.py,
# tests/test_carry_stream.py and the dryrun harness.

_BIG_IDX = 2**30  # > any global spot index; int so jnp weak-types it


def _chunked_partial_step(chunk_xs, Sc, carry, slot):
    """Best-fit-with-gaps placement of one pod slot over spot chunks:
    the shared delta-form elect-then-commit step
    (solver/ffd._stream_bf_step); feasibility tracking is repair's job,
    so the any-fit flag is dropped."""
    state, (chosen, _) = _stream_bf_step(chunk_xs, Sc, carry, slot)
    return state, chosen


def _chunked_repair_round(small, chunk_xs, chain, Sc, state, round_idx):
    """One elect-then-commit repair round (bit-identical to
    ``_repair_round``): chunk-local sweeps build the unlocker set and
    re-placement targets, elections pick the move in global index
    order, the exact affinity gate vets it, and only the winning
    chunks' state commits. State is the stacked delta carry."""
    spot_aff_static, slot_req, slot_valid, slot_tol, slot_aff = small
    free0_c, count0_c, aff0_c, taints_c, ok_c, maxp_c, offs = chunk_xs
    used_c, dcount_c, daff_c, assign = state
    C, K, R = slot_req.shape
    Sp = used_c.shape[0] * Sc
    ks = jnp.arange(K)[None, :]
    gsc = jnp.arange(Sc)[None, :]

    unplaced = slot_valid & (assign < 0)  # [C, K]
    has_gap = jnp.any(unplaced, axis=-1)
    p = jnp.argmax(unplaced, axis=-1)

    req_p = jnp.take_along_axis(slot_req, p[:, None, None], axis=1)[:, 0]
    tol_p = jnp.take_along_axis(slot_tol, p[:, None, None], axis=1)[:, 0]
    aff_p = jnp.take_along_axis(slot_aff, p[:, None, None], axis=1)[:, 0]

    placed = assign >= 0  # [C, K]
    s_q = jnp.clip(assign, 0, Sp - 1)  # [C, K] global node per pod
    req_t = jnp.swapaxes(slot_req, 1, 2)  # [C, R, K]

    # ---- sweep A (elect): chunk-local unlocker candidates. Each placed
    # pod lives in exactly one chunk, so the union over chunks is the
    # unchunked unlock mask exactly.
    def sweep_unlock(unlock, xs):
        used_j, free0_j, taints_j, ok_j, off = xs
        free_j = free0_j - used_j.astype(free0_j.dtype)
        word_ok = jnp.all(
            (taints_j & ~tol_p[:, :, None]) == 0, axis=1
        )  # [C, Sc]
        static_p = word_ok & ok_j
        in_j = (s_q >= off) & (s_q < off + Sc)  # [C, K]
        loc = jnp.clip(s_q - off, 0, Sc - 1)
        free_at_q = jnp.take_along_axis(
            free_j, loc[:, None, :], axis=2
        )  # [C, R, K]
        res_ok = jnp.all(free_at_q + req_t - req_p[:, :, None] >= 0, axis=1)
        static_at_q = jnp.take_along_axis(static_p, loc, axis=1)
        return unlock | (placed & in_j & res_ok & static_at_q), None

    unlock, _ = jax.lax.scan(
        sweep_unlock,
        jnp.zeros((C, K), bool),
        (used_c, free0_c, taints_c, ok_c, offs),
    )

    # q election: deterministic rotation in global slot order, unchanged
    n_unlock = unlock.sum(axis=-1)
    rank = jnp.cumsum(unlock, axis=-1) - 1
    want = jnp.where(
        n_unlock > 0, round_idx % jnp.maximum(n_unlock, 1), -1
    )
    is_q = unlock & (rank == want[:, None])
    q = jnp.argmax(is_q, axis=-1)
    any_q = jnp.any(is_q, axis=-1)

    req_q = jnp.take_along_axis(slot_req, q[:, None, None], axis=1)[:, 0]
    tol_q = jnp.take_along_axis(slot_tol, q[:, None, None], axis=1)[:, 0]
    aff_q = jnp.take_along_axis(slot_aff, q[:, None, None], axis=1)[:, 0]
    sq_star = jnp.take_along_axis(s_q, q[:, None], axis=1)[:, 0]

    # ---- sweep B (elect): q's first-fit re-placement target — the
    # minimum over chunk-local winners' global indices IS the global
    # first fit — plus (chain) the chunk-local r candidates.
    def sweep_q(carry_b, xs):
        s2g, eligible_r = carry_b
        (used_j, dcount_j, daff_j, free0_j, count0_j, aff0_j,
         taints_j, ok_j, maxp_j, off) = xs
        free_j, count_j, aff_j = _widen_chunk(
            free0_j, count0_j, aff0_j, used_j, dcount_j, daff_j
        )
        fits_q = fit_mask_t(
            jnp,
            free_t=free_j,
            count=count_j,
            max_pods=maxp_j,
            node_taints_t=taints_j,
            node_ok=ok_j,
            node_aff_t=aff_j,
            req=req_q,
            tol=tol_q,
            aff=aff_q,
        )  # [C, Sc]
        gid = off + gsc
        fits_q &= gid != sq_star[:, None]
        first = jnp.argmax(fits_q, axis=-1).astype(jnp.int32)
        cand = jnp.where(jnp.any(fits_q, axis=-1), off + first, _BIG_IDX)
        s2g = jnp.minimum(s2g, cand)
        if chain:
            word_ok_q = jnp.all(
                (taints_j & ~tol_q[:, :, None]) == 0, axis=1
            )
            static_q = word_ok_q & ok_j
            in_j = (s_q >= off) & (s_q < off + Sc)
            loc = jnp.clip(s_q - off, 0, Sc - 1)
            free_at_q = jnp.take_along_axis(free_j, loc[:, None, :], axis=2)
            res_ok_r = jnp.all(
                free_at_q + req_t - req_q[:, :, None] >= 0, axis=1
            )
            static_q_at = jnp.take_along_axis(static_q, loc, axis=1)
            eligible_r = eligible_r | (
                placed
                & in_j
                & (s_q != sq_star[:, None])
                & static_q_at
                & res_ok_r
            )
        return (s2g, eligible_r), None

    (s2g, eligible_r), _ = jax.lax.scan(
        sweep_q,
        (
            jnp.full((C,), _BIG_IDX, jnp.int32),
            jnp.zeros((C, K), bool),
        ),
        (used_c, dcount_c, daff_c, *chunk_xs),
    )
    can_move = s2g < _BIG_IDX

    if chain:
        # r election: independent rotation schedule (see _repair_round)
        n_r = eligible_r.sum(axis=-1)
        rank_r = jnp.cumsum(eligible_r, axis=-1) - 1
        want_r = jnp.where(
            n_r > 0,
            (round_idx // jnp.maximum(n_unlock, 1)) % jnp.maximum(n_r, 1),
            -1,
        )
        is_r = eligible_r & (rank_r == want_r[:, None])
        r = jnp.argmax(is_r, axis=-1)
        any_r = jnp.any(is_r, axis=-1)
        sr_star = jnp.take_along_axis(s_q, r[:, None], axis=1)[:, 0]
        req_r = jnp.take_along_axis(slot_req, r[:, None, None], axis=1)[:, 0]
        tol_r = jnp.take_along_axis(slot_tol, r[:, None, None], axis=1)[:, 0]
        aff_r = jnp.take_along_axis(slot_aff, r[:, None, None], axis=1)[:, 0]

        # ---- sweep C (elect): r's re-placement target
        def sweep_r(s3g, xs):
            (used_j, dcount_j, daff_j, free0_j, count0_j, aff0_j,
             taints_j, ok_j, maxp_j, off) = xs
            free_j, count_j, aff_j = _widen_chunk(
                free0_j, count0_j, aff0_j, used_j, dcount_j, daff_j
            )
            fits_r = fit_mask_t(
                jnp,
                free_t=free_j,
                count=count_j,
                max_pods=maxp_j,
                node_taints_t=taints_j,
                node_ok=ok_j,
                node_aff_t=aff_j,
                req=req_r,
                tol=tol_r,
                aff=aff_r,
            )
            gid = off + gsc
            fits_r &= (gid != sr_star[:, None]) & (gid != sq_star[:, None])
            first = jnp.argmax(fits_r, axis=-1).astype(jnp.int32)
            cand = jnp.where(
                jnp.any(fits_r, axis=-1), off + first, _BIG_IDX
            )
            return jnp.minimum(s3g, cand), None

        s3g, _ = jax.lax.scan(
            sweep_r,
            jnp.full((C,), _BIG_IDX, jnp.int32),
            (used_c, dcount_c, daff_c, *chunk_xs),
        )
        r_can_move = s3g < _BIG_IDX

    # ---- exact affinity gates: O(K·A), no spot-wide work. aff_ejd /
    # aff_ejd_r are the pod-contributed halves (the delta write values);
    # the gates OR the static bits back in, exactly as _repair_round.
    others = placed & (assign == sq_star[:, None]) & (ks != q[:, None])
    contrib = jnp.where(
        others[:, None, :], jnp.swapaxes(slot_aff, 1, 2), jnp.uint32(0)
    )
    aff_ejd = jax.lax.reduce(
        contrib, np.uint32(0), jax.lax.bitwise_or, (2,)
    )
    aff_ej = aff_ejd | spot_aff_static[sq_star]
    aff_ok_p = jnp.all((aff_p & aff_ej) == 0, axis=1)
    do_direct = has_gap & any_q & can_move & aff_ok_p

    if not chain:
        do_chain = jnp.zeros_like(do_direct)
        sr_star = s2g
        s3g = s2g
        req_r = req_q
        aff_r = aff_q
        aff_ejd_r = aff_ejd
        r = q
    else:
        others_r = placed & (assign == sr_star[:, None]) & (ks != r[:, None])
        contrib_r = jnp.where(
            others_r[:, None, :], jnp.swapaxes(slot_aff, 1, 2), jnp.uint32(0)
        )
        aff_ejd_r = jax.lax.reduce(
            contrib_r, np.uint32(0), jax.lax.bitwise_or, (2,)
        )
        aff_ej_r = aff_ejd_r | spot_aff_static[sr_star]
        aff_ok_q = jnp.all((aff_q & aff_ej_r) == 0, axis=1)
        do_chain = (
            has_gap & any_q & ~can_move & aff_ok_p
            & any_r & r_can_move & aff_ok_q
        )
    do = do_direct | do_chain

    q_dest = jnp.where(do_chain, sr_star, s2g)
    inc_node = jnp.where(do_chain, s3g, s2g)
    dt = daff_c.dtype
    zero = jnp.zeros((), dt)
    qd_col = jnp.where(
        do_chain[:, None], aff_ejd_r | aff_q, jnp.uint32(0)
    ).astype(dt)

    # ---- COMMIT: only chunks holding a touched node change state
    def commit(xs):
        used_j, dcount_j, daff_j, off = xs
        gid = off + gsc
        onehot_sq = gid == sq_star[:, None]  # [C, Sc]
        onehot_qd = gid == q_dest[:, None]
        onehot_s3 = (gid == s3g[:, None]) & do_chain[:, None]
        onehot_inc = gid == inc_node[:, None]
        delta = (
            onehot_sq[:, None, :] * (req_q - req_p)[:, :, None]
            - onehot_qd[:, None, :] * req_q[:, :, None]
            + onehot_qd[:, None, :]
            * do_chain[:, None, None]
            * req_r[:, :, None]
            - onehot_s3[:, None, :] * req_r[:, :, None]
        )
        used_j = jnp.where(
            do[:, None, None],
            (used_j.astype(delta.dtype) - delta).astype(used_j.dtype),
            used_j,
        )
        dcount_j = jnp.where(
            do[:, None],
            dcount_j + onehot_inc.astype(dcount_j.dtype),
            dcount_j,
        )
        daff_after = jnp.where(
            onehot_sq[:, None, :],
            (aff_ejd | aff_p).astype(dt)[:, :, None],
            daff_j,
        )
        daff_after = jnp.where(
            (onehot_qd & do_chain[:, None])[:, None, :],
            qd_col[:, :, None],
            daff_after,
        ) | jnp.where(
            (onehot_qd & do_direct[:, None])[:, None, :],
            aff_q.astype(dt)[:, :, None],
            zero,
        ) | jnp.where(
            onehot_s3[:, None, :], aff_r.astype(dt)[:, :, None], zero
        )
        daff_j = jnp.where(do[:, None, None], daff_after, daff_j)
        return used_j, dcount_j, daff_j

    used_c, dcount_c, daff_c = jax.lax.map(
        commit, (used_c, dcount_c, daff_c, offs)
    )
    assign = jnp.where(
        do[:, None],
        jnp.where(
            ks == p[:, None],
            sq_star[:, None].astype(assign.dtype),
            jnp.where(
                ks == q[:, None],
                q_dest[:, None].astype(assign.dtype),
                jnp.where(
                    (ks == r[:, None]) & do_chain[:, None],
                    s3g[:, None].astype(assign.dtype),
                    assign,
                ),
            ),
        ),
        assign,
    )
    return (used_c, dcount_c, daff_c, assign), ()


def plan_repair_chunked(
    packed: PackedCluster,
    rounds: int = DEFAULT_ROUNDS,
    chain: bool = True,
    spot_chunks: int = 2,
    layout: CarryLayout = WIDE_LAYOUT,
) -> SolveResult:
    """``plan_repair`` restructured over ``spot_chunks`` ordered spot
    chunks (elect-then-commit; see the module section above) —
    bit-identical results, per-round temporaries O(S / spot_chunks) and
    the carried state narrow under ``layout`` (solver/carry.py).
    The spot axis is padded to a chunk multiple with inert nodes
    (``spot_ok``=False, at the end of the probe order), so placements
    and assignment indices are unchanged; validation runs against the
    ORIGINAL packed problem."""
    if spot_chunks <= 1:
        return plan_repair(packed, rounds=rounds, chain=chain, layout=layout)
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    A = packed.spot_aff.shape[1]
    n = int(spot_chunks)
    Sc = -(-S // n)
    pad = n * Sc - S

    chunk_xs = chunked_spot_statics(packed, n, Sc)
    state0 = _zero_chunk_state(layout, n, C, R, A, Sc)

    slots = _slot_stream(packed)
    (used_c, dcount_c, daff_c), chosen = jax.lax.scan(
        functools.partial(_chunked_partial_step, chunk_xs, Sc),
        state0,
        slots,
    )
    assign0 = jnp.swapaxes(chosen, 0, 1).astype(jnp.int32)  # [C, K]

    small = (
        pad_spot_axis(packed.spot_aff, pad),  # static resident bits, [Sp, A]
        jnp.asarray(packed.slot_req),
        jnp.asarray(packed.slot_valid),
        jnp.asarray(packed.slot_tol),
        jnp.asarray(packed.slot_aff),
    )
    state = (used_c, dcount_c, daff_c, assign0)
    state, _ = jax.lax.scan(
        functools.partial(_chunked_repair_round, small, chunk_xs, chain, Sc),
        state,
        jnp.arange(rounds),
    )
    assign = state[3]

    feasible = validate_assignment(jnp, packed, assign)
    assignment = jnp.where(feasible[:, None], assign, -1)
    return SolveResult(feasible=feasible, assignment=assignment)


plan_repair_chunked_jit = jax.jit(
    plan_repair_chunked,
    static_argnames=("rounds", "chain", "spot_chunks", "layout"),
)


def plan_repair_oracle(
    packed: PackedCluster, rounds: int = DEFAULT_ROUNDS, chain: bool = True
) -> SolveResult:
    """Serial NumPy mirror of ``plan_repair`` — identical partial pass,
    rotation, exact affinity ejection, and validation, for bit-parity
    tests against the device solver. ``chain=False`` mirrors the
    depth-1-only analyzer variant."""
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    assign = np.full((C, K), -1, np.int32)
    frees = np.broadcast_to(packed.spot_free, (C, S, R)).copy()
    counts = np.broadcast_to(packed.spot_count, (C, S)).astype(np.int64).copy()
    affs = np.broadcast_to(packed.spot_aff, (C, *packed.spot_aff.shape)).copy()

    # partial best-fit pass with gaps
    for c in range(C):
        for k in range(K):
            if not packed.slot_valid[c, k]:
                continue
            fits = fit_mask(
                np,
                free=frees[c],
                count=counts[c],
                max_pods=packed.spot_max_pods,
                node_taints=packed.spot_taints,
                node_ok=packed.spot_ok,
                node_aff=affs[c],
                req=packed.slot_req[c, k],
                tol=packed.slot_tol[c, k],
                aff=packed.slot_aff[c, k],
            )
            if not fits.any():
                continue  # leave the gap for repair
            slack = np.where(
                fits, frees[c, :, 0] - packed.slot_req[c, k, 0], np.inf
            )
            s = int(np.argmin(slack))
            assign[c, k] = s
            frees[c, s] -= packed.slot_req[c, k]
            counts[c, s] += 1
            affs[c, s] |= packed.slot_aff[c, k]

    for rnd in range(rounds):
        for c in range(C):
            unplaced = packed.slot_valid[c] & (assign[c] < 0)
            if not unplaced.any():
                continue
            p = int(np.argmax(unplaced))
            req_p = packed.slot_req[c, p]
            tol_p = packed.slot_tol[c, p]
            aff_p = packed.slot_aff[c, p]
            static_p = (
                np.all((packed.spot_taints & ~tol_p) == 0, axis=-1)
                & packed.spot_ok
            )
            unlock = np.zeros(K, bool)
            for k in range(K):
                s = assign[c, k]
                if s < 0:
                    continue
                if not static_p[s]:
                    continue
                if not np.all(
                    frees[c, s] + packed.slot_req[c, k] - req_p >= 0
                ):
                    continue
                unlock[k] = True
            n_unlock = int(unlock.sum())
            if not n_unlock:
                continue
            want = rnd % n_unlock
            q = int(np.flatnonzero(unlock)[want])
            sq = int(assign[c, q])
            # exact aff of q's node after q leaves (device lockstep):
            # static resident bits OR pods still assigned there
            aff_ej = np.asarray(packed.spot_aff[sq]).copy()
            for k in range(K):
                if k != q and assign[c, k] == sq:
                    aff_ej |= packed.slot_aff[c, k]
            if np.any(aff_p & aff_ej):
                continue  # rotation tries a different unlocker next round
            req_q = packed.slot_req[c, q]
            tol_q = packed.slot_tol[c, q]
            aff_q = packed.slot_aff[c, q]
            fits_q = fit_mask(
                np,
                free=frees[c],
                count=counts[c],
                max_pods=packed.spot_max_pods,
                node_taints=packed.spot_taints,
                node_ok=packed.spot_ok,
                node_aff=affs[c],
                req=req_q,
                tol=tol_q,
                aff=aff_q,
            )
            fits_q[sq] = False
            if fits_q.any():
                # depth-1 direct move: p -> s_q, q -> s2
                s2 = int(np.argmax(fits_q))
                assign[c, p] = sq
                assign[c, q] = s2
                frees[c, sq] += req_q - req_p
                frees[c, s2] -= req_q
                counts[c, s2] += 1
                affs[c, s2] |= aff_q
                affs[c, sq] = aff_ej | aff_p  # exact replacement, not OR
                continue
            if not chain:
                continue  # depth-1-only analyzer variant
            # depth-2 chain (device lockstep): q cannot re-place
            # directly; move it onto a third pod r's node and re-place
            # r elsewhere (p -> s_q, q -> s_r, r -> s3)
            static_q = (
                np.all((packed.spot_taints & ~tol_q) == 0, axis=-1)
                & packed.spot_ok
            )
            eligible = np.zeros(K, bool)
            for k in range(K):
                s = assign[c, k]
                if s < 0 or s == sq:
                    continue
                if not static_q[s]:
                    continue
                if not np.all(frees[c, s] + packed.slot_req[c, k] - req_q >= 0):
                    continue
                eligible[k] = True
            n_r = int(eligible.sum())
            if not n_r:
                continue
            # independent r rotation (device lockstep): see _repair_round
            r = int(np.flatnonzero(eligible)[(rnd // max(n_unlock, 1)) % n_r])
            sr = int(assign[c, r])
            fits_r = fit_mask(
                np,
                free=frees[c],
                count=counts[c],
                max_pods=packed.spot_max_pods,
                node_taints=packed.spot_taints,
                node_ok=packed.spot_ok,
                node_aff=affs[c],
                req=packed.slot_req[c, r],
                tol=packed.slot_tol[c, r],
                aff=packed.slot_aff[c, r],
            )
            fits_r[sr] = False
            fits_r[sq] = False
            if not fits_r.any():
                continue  # rotation elects a different r next round
            s3 = int(np.argmax(fits_r))
            aff_ej_r = np.asarray(packed.spot_aff[sr]).copy()
            for k in range(K):
                if k != r and assign[c, k] == sr:
                    aff_ej_r |= packed.slot_aff[c, k]
            if np.any(aff_q & aff_ej_r):
                continue
            assign[c, p] = sq
            assign[c, q] = sr
            assign[c, r] = s3
            frees[c, sq] += req_q - req_p
            frees[c, sr] += packed.slot_req[c, r] - req_q
            frees[c, s3] -= packed.slot_req[c, r]
            counts[c, s3] += 1
            affs[c, sq] = aff_ej | aff_p
            affs[c, sr] = aff_ej_r | aff_q
            affs[c, s3] |= packed.slot_aff[c, r]

    feasible = np.asarray(validate_assignment(np, packed, assign))
    assignment = np.where(feasible[:, None], assign, -1).astype(np.int32)
    return SolveResult(feasible=feasible, assignment=assignment)


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): both repair variants traced at audit shapes —
# the chunked carry restructure is exactly where ROADMAP-5's narrow-int
# packing landed, so its dtype/width properties are gated here (the
# chunked probe runs the NARROW layout the 20x tier dispatches).
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)
from k8s_spot_rescheduler_tpu.solver.carry import NARROW_LAYOUT  # noqa: E402

HOT_PROGRAMS = {
    "repair.rounds": HotProgram(
        build=lambda s: (
            functools.partial(plan_repair, rounds=4),
            (packed_struct(s),),
        ),
        covers=("solver.repair:plan_repair",),
    ),
    "repair.chunked": HotProgram(
        build=lambda s: (
            functools.partial(
                plan_repair_chunked, rounds=4, spot_chunks=4,
                layout=NARROW_LAYOUT,
            ),
            (packed_struct(s),),
        ),
        covers=("solver.repair:plan_repair_chunked",),
    ),
}
