"""Bounded eject-and-reinsert local search — the "+ local-search" half
of the north-star kernel (SURVEY.md §7 step 5, BASELINE.md).

Greedy packing (first-fit or best-fit decreasing) fails a candidate lane
the moment one pod fits nowhere, even when relocating a single
already-placed pod would unlock it — the regime where the reference's
serial probe nest (reference rescheduler.go:334-370) and any one-pass
heuristic lose drains at high spot utilization. This module recovers
those lanes:

1. **Partial pass** — the best-fit-decreasing scan of solver/ffd.py but
   *continue on failure*: place every pod that fits, leave gaps
   (``assignment == -1``) instead of aborting the lane.
2. **Repair rounds** — a fixed-length ``lax.scan``; each round, every
   unfinished lane in parallel picks its first unplaced pod ``p``,
   searches the already-placed pods ``q`` whose ejection would let
   ``p`` take their node, rotates deterministically through those
   unlockers across rounds, and executes the relocation
   ``q → elsewhere, p → q's node`` when ``q`` itself re-places. When
   ``q`` CANNOT re-place directly, a depth-2 CHAIN (round 4) relocates
   it onto a third pod ``r``'s node and re-places ``r`` elsewhere
   (``p → s_q, q → s_r, r → s3``) — closing the two-pod interlock that
   defeated depth-1 (the published boundary moves to three-link
   chains, docs/RESULTS.md).
3. **Validation** — the final assignment is re-proven from scratch
   (solver/validate.py) on device; only fully-placed, predicate-valid
   lanes report feasible. The search can therefore never approve an
   invalid drain, no matter what (hard part (e): conservative only).

TPU shape discipline matches solver/ffd.py: carries keep the spot axis
minor ([C, R, S] / [C, A, S]), shapes are static, rounds are a scan.

Affinity ejection is EXACT (round 4; was monotone-conservative before):
the per-node affinity state starts exact after the partial pass (static
resident bits OR placed pods' bits — no ejections yet) and every
relocation recomputes the ejected node's word from scratch (static bits
OR the bits of pods still assigned there), so ejecting ``q`` genuinely
clears its group bits and affinity-driven unlocks — a group member
vacating the node its group-mate needs — are found. The unlock
*election* stays cheap (resources + static words only); the elected
move is gated by the exact recompute, and the deterministic rotation
tries a different unlocker next round when the gate fails. Every final
assignment is still re-proven from scratch, so no exactness bug can
ever approve an invalid drain.

Cost: each round is O(K·(R+A) + S·(R+W)) per lane vs the greedy scan's
O(K·S·(R+W)) — ``ROUNDS`` rounds add well under 2x total solve time.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.predicates.masks import fit_mask, fit_mask_t
from k8s_spot_rescheduler_tpu.solver.ffd import _Carry, _scan_step
from k8s_spot_rescheduler_tpu.solver.result import SolveResult
from k8s_spot_rescheduler_tpu.solver.validate import validate_assignment

DEFAULT_ROUNDS = 8


class _RepairCarry(NamedTuple):
    free: jax.Array  # f32 [C, R, S]
    count: jax.Array  # i32 [C, S]
    aff: jax.Array  # u32 [C, A, S] (exact — see module docstring)
    assign: jax.Array  # i32 [C, K]


def _partial_scan_step(static, carry: _Carry, slot):
    """solver/ffd.py ``_scan_step`` in best-fit mode, but a pod that fits
    nowhere leaves a gap instead of failing the lane."""
    new_carry, chosen = _scan_step(static, True, carry, slot)
    # keep scanning: feasibility tracking is repair's job now
    return new_carry._replace(feasible=carry.feasible), chosen


def _repair_round(static, chain, state: _RepairCarry, round_idx):
    """``chain`` (compile-time bool) gates the depth-2 block — the
    chain-depth-demand analyzer (bench/chain_depth.py) compiles a
    depth-1-only variant to classify which lanes genuinely NEED the
    chain; production always passes True."""
    (spot_max_pods, spot_taints_t, spot_ok, spot_aff_static,
     slot_req, slot_valid, slot_tol, slot_aff) = static
    C, K, R = slot_req.shape
    S = state.free.shape[-1]

    unplaced = slot_valid & (state.assign < 0)  # [C, K]
    has_gap = jnp.any(unplaced, axis=-1)  # [C]
    p = jnp.argmax(unplaced, axis=-1)  # first unplaced slot per lane

    req_p = jnp.take_along_axis(slot_req, p[:, None, None], axis=1)[:, 0]
    tol_p = jnp.take_along_axis(slot_tol, p[:, None, None], axis=1)[:, 0]
    aff_p = jnp.take_along_axis(slot_aff, p[:, None, None], axis=1)[:, 0]

    # static admission of p per spot node (taints/selector words + ok)
    word_ok = jnp.all(
        (spot_taints_t & ~tol_p[:, :, None]) == 0, axis=1
    )  # [C, S]
    static_p = word_ok & spot_ok  # [C, S]

    placed = state.assign >= 0  # [C, K]
    s_q = jnp.clip(state.assign, 0, S - 1)  # [C, K]

    # would p fit on q's node if q were ejected? (resources + static
    # words; the affinity gate is exact and applied to the ELECTED
    # unlocker below — a per-candidate exact recompute here would cost
    # O(K^2·A) for nothing, since rotation retries next round anyway)
    free_at_q = jnp.take_along_axis(
        state.free, s_q[:, None, :], axis=2
    )  # [C, R, K]
    req_t = jnp.swapaxes(slot_req, 1, 2)  # [C, R, K]
    res_ok = jnp.all(
        free_at_q + req_t - req_p[:, :, None] >= 0, axis=1
    )  # [C, K]
    static_at_q = jnp.take_along_axis(static_p, s_q, axis=1)  # [C, K]

    unlock = placed & res_ok & static_at_q  # [C, K]
    n_unlock = unlock.sum(axis=-1)  # [C]

    # deterministic rotation: try a different unlocker each round
    rank = jnp.cumsum(unlock, axis=-1) - 1
    want = jnp.where(
        n_unlock > 0, round_idx % jnp.maximum(n_unlock, 1), -1
    )
    is_q = unlock & (rank == want[:, None])
    q = jnp.argmax(is_q, axis=-1)  # [C]
    any_q = jnp.any(is_q, axis=-1)

    # can q itself re-place somewhere else under current state?
    req_q = jnp.take_along_axis(slot_req, q[:, None, None], axis=1)[:, 0]
    tol_q = jnp.take_along_axis(slot_tol, q[:, None, None], axis=1)[:, 0]
    aff_q = jnp.take_along_axis(slot_aff, q[:, None, None], axis=1)[:, 0]
    sq_star = jnp.take_along_axis(s_q, q[:, None], axis=1)[:, 0]  # [C]

    fits_q = fit_mask_t(
        jnp,
        free_t=state.free,
        count=state.count,
        max_pods=spot_max_pods,
        node_taints_t=spot_taints_t,
        node_ok=spot_ok,
        node_aff_t=state.aff,
        req=req_q,
        tol=tol_q,
        aff=aff_q,
    )  # [C, S]
    fits_q &= jnp.arange(S)[None, :] != sq_star[:, None]
    s2 = jnp.argmax(fits_q, axis=-1)  # [C]
    can_move = jnp.any(fits_q, axis=-1)

    # exact affinity of q's node AFTER q leaves: static resident bits OR
    # the bits of pods still assigned there — ejection genuinely clears
    # q's contribution (a group member vacating for its group-mate)
    ks = jnp.arange(K)[None, :]
    others = placed & (state.assign == sq_star[:, None]) & (ks != q[:, None])
    contrib = jnp.where(
        others[:, None, :], jnp.swapaxes(slot_aff, 1, 2), jnp.uint32(0)
    )  # [C, A, K]
    aff_ej = jax.lax.reduce(
        contrib, np.uint32(0), jax.lax.bitwise_or, (2,)
    ) | spot_aff_static[sq_star]  # [C, A]
    aff_ok_p = jnp.all((aff_p & aff_ej) == 0, axis=1)  # [C]

    do_direct = has_gap & any_q & can_move & aff_ok_p  # [C]

    if not chain:
        # depth-1-only variant: no chain block compiles at all; the
        # masked arithmetic below folds to the direct move
        do_chain = jnp.zeros_like(do_direct)
        sr_star = s2
        s3 = s2
        req_r = req_q
        aff_r = aff_q
        aff_ej_r = aff_ej
        r = q

    # ---- depth-2 chain (round 4): when q cannot re-place DIRECTLY,
    # relocate it onto a third pod r's node and re-place r elsewhere
    # (p -> s_q, q -> s_r, r -> s3) — the two-pod interlock that
    # defeated depth-1 (docs/RESULTS.md boundary). r is elected by the
    # same rotation; its own re-placement and both exact affinity gates
    # are verified post-election, with rotation retrying on failure.
    if chain:
        word_ok_q = jnp.all(
            (spot_taints_t & ~tol_q[:, :, None]) == 0, axis=1
        )  # [C, S]
        static_q = word_ok_q & spot_ok
        static_q_at = jnp.take_along_axis(static_q, s_q, axis=1)  # [C, K]
        res_ok_r = jnp.all(
            free_at_q + req_t - req_q[:, :, None] >= 0, axis=1
        )  # [C, K] — q fits r's node once r is ejected
        eligible_r = (
            placed & (s_q != sq_star[:, None]) & static_q_at & res_ok_r
        )  # [C, K]
        n_r = eligible_r.sum(axis=-1)
        rank_r = jnp.cumsum(eligible_r, axis=-1) - 1
        # r rotates on an INDEPENDENT schedule (divided by the q-rotation
        # period): keying both to round_idx would lock the pairings to
        # q ≡ r (mod gcd(n_unlock, n_r)) and leave whole (q, r) pairs
        # unreachable at any round count (round-4 review finding); this way
        # n_unlock x n_r rounds sweep every pairing
        want_r = jnp.where(
            n_r > 0,
            (round_idx // jnp.maximum(n_unlock, 1)) % jnp.maximum(n_r, 1),
            -1,
        )
        is_r = eligible_r & (rank_r == want_r[:, None])
        r = jnp.argmax(is_r, axis=-1)  # [C]
        any_r = jnp.any(is_r, axis=-1)
        sr_star = jnp.take_along_axis(s_q, r[:, None], axis=1)[:, 0]  # [C]
        req_r = jnp.take_along_axis(slot_req, r[:, None, None], axis=1)[:, 0]
        tol_r = jnp.take_along_axis(slot_tol, r[:, None, None], axis=1)[:, 0]
        aff_r = jnp.take_along_axis(slot_aff, r[:, None, None], axis=1)[:, 0]

        fits_r = fit_mask_t(
            jnp,
            free_t=state.free,
            count=state.count,
            max_pods=spot_max_pods,
            node_taints_t=spot_taints_t,
            node_ok=spot_ok,
            node_aff_t=state.aff,
            req=req_r,
            tol=tol_r,
            aff=aff_r,
        )  # [C, S]
        fits_r &= (jnp.arange(S)[None, :] != sr_star[:, None]) & (
            jnp.arange(S)[None, :] != sq_star[:, None]
        )
        s3 = jnp.argmax(fits_r, axis=-1)  # [C]
        r_can_move = jnp.any(fits_r, axis=-1)

        # exact affinity of r's node after r leaves, for q's arrival
        others_r = placed & (state.assign == sr_star[:, None]) & (
            ks != r[:, None]
        )
        contrib_r = jnp.where(
            others_r[:, None, :], jnp.swapaxes(slot_aff, 1, 2), jnp.uint32(0)
        )
        aff_ej_r = jax.lax.reduce(
            contrib_r, np.uint32(0), jax.lax.bitwise_or, (2,)
        ) | spot_aff_static[sr_star]  # [C, A]
        aff_ok_q = jnp.all((aff_q & aff_ej_r) == 0, axis=1)  # [C]

        do_chain = (
            has_gap & any_q & ~can_move & aff_ok_p
            & any_r & r_can_move & aff_ok_q
        )
    do = do_direct | do_chain  # [C]

    # q's destination: s2 (direct) or r's node (chain); the +1 pod count
    # lands on s2 (direct) or s3 (chain) — every other count nets zero
    q_dest = jnp.where(do_chain, sr_star, s2)
    inc_node = jnp.where(do_chain, s3, s2)
    onehot_sq = jnp.arange(S)[None, :] == sq_star[:, None]  # [C, S]
    onehot_qd = jnp.arange(S)[None, :] == q_dest[:, None]
    onehot_s3 = (jnp.arange(S)[None, :] == s3[:, None]) & do_chain[:, None]
    onehot_inc = jnp.arange(S)[None, :] == inc_node[:, None]
    delta = (
        onehot_sq[:, None, :] * (req_q - req_p)[:, :, None]
        - onehot_qd[:, None, :] * req_q[:, :, None]
        + onehot_qd[:, None, :] * do_chain[:, None, None] * req_r[:, :, None]
        - onehot_s3[:, None, :] * req_r[:, :, None]
    )
    free = jnp.where(do[:, None, None], state.free + delta, state.free)
    count = jnp.where(
        do[:, None], state.count + onehot_inc.astype(state.count.dtype),
        state.count,
    )
    # s_q's column is REPLACED by the exact recompute (plus p's
    # arrival); q's destination is replaced on a chain (aff_ej_r | q's
    # bits) or OR'd on a direct move; s3 accumulates r's bits
    qd_col = jnp.where(
        do_chain[:, None], aff_ej_r | aff_q, jnp.uint32(0)
    )  # chain: exact replacement value for s_r
    aff_after = jnp.where(
        onehot_sq[:, None, :], (aff_ej | aff_p)[:, :, None], state.aff
    )
    aff_after = jnp.where(
        (onehot_qd & do_chain[:, None])[:, None, :],
        qd_col[:, :, None],
        aff_after,
    ) | jnp.where(
        (onehot_qd & do_direct[:, None])[:, None, :],
        aff_q[:, :, None],
        jnp.uint32(0),
    ) | jnp.where(onehot_s3[:, None, :], aff_r[:, :, None], jnp.uint32(0))
    aff = jnp.where(do[:, None, None], aff_after, state.aff)
    assign = jnp.where(
        do[:, None],
        jnp.where(
            ks == p[:, None],
            sq_star[:, None].astype(state.assign.dtype),
            jnp.where(
                ks == q[:, None], q_dest[:, None].astype(state.assign.dtype),
                jnp.where(
                    (ks == r[:, None]) & do_chain[:, None],
                    s3[:, None].astype(state.assign.dtype),
                    state.assign,
                ),
            ),
        ),
        state.assign,
    )
    return _RepairCarry(free, count, aff, assign), ()


def plan_repair(
    packed: PackedCluster, rounds: int = DEFAULT_ROUNDS, chain: bool = True
) -> SolveResult:
    """Jittable partial-pack + bounded repair + from-scratch validation.
    ``chain=False`` compiles the depth-1-only search — used solely by
    the chain-depth-demand analyzer (bench/chain_depth.py)."""
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]

    free_t = jnp.asarray(packed.spot_free).T
    aff_t = jnp.asarray(packed.spot_aff).T
    carry = _Carry(
        free=jnp.broadcast_to(free_t, (C, *free_t.shape)),
        count=jnp.broadcast_to(packed.spot_count, (C, S)).astype(jnp.int32),
        aff=jnp.broadcast_to(aff_t, (C, *aff_t.shape)),
        feasible=jnp.asarray(packed.cand_valid),
    )
    scan_static = (
        jnp.asarray(packed.spot_max_pods),
        jnp.asarray(packed.spot_taints).T,
        jnp.asarray(packed.spot_ok),
    )
    slots = (
        jnp.moveaxis(packed.slot_req, 1, 0),
        jnp.moveaxis(packed.slot_valid, 1, 0),
        jnp.moveaxis(packed.slot_tol, 1, 0),
        jnp.moveaxis(packed.slot_aff, 1, 0),
    )
    carry, chosen = jax.lax.scan(
        functools.partial(_partial_scan_step, scan_static), carry, slots
    )
    assign0 = jnp.swapaxes(chosen, 0, 1).astype(jnp.int32)  # [C, K]

    state = _RepairCarry(
        free=carry.free, count=carry.count, aff=carry.aff, assign=assign0
    )
    repair_static = (
        *scan_static,
        jnp.asarray(packed.spot_aff),  # static resident bits, [S, A]
        jnp.asarray(packed.slot_req),
        jnp.asarray(packed.slot_valid),
        jnp.asarray(packed.slot_tol),
        jnp.asarray(packed.slot_aff),
    )
    state, _ = jax.lax.scan(
        functools.partial(_repair_round, repair_static, chain),
        state,
        jnp.arange(rounds),
    )

    feasible = validate_assignment(jnp, packed, state.assign)
    assignment = jnp.where(feasible[:, None], state.assign, -1)
    return SolveResult(feasible=feasible, assignment=assignment)


plan_repair_jit = jax.jit(plan_repair, static_argnames=("rounds", "chain"))


def plan_repair_oracle(
    packed: PackedCluster, rounds: int = DEFAULT_ROUNDS, chain: bool = True
) -> SolveResult:
    """Serial NumPy mirror of ``plan_repair`` — identical partial pass,
    rotation, exact affinity ejection, and validation, for bit-parity
    tests against the device solver. ``chain=False`` mirrors the
    depth-1-only analyzer variant."""
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    assign = np.full((C, K), -1, np.int32)
    frees = np.broadcast_to(packed.spot_free, (C, S, R)).copy()
    counts = np.broadcast_to(packed.spot_count, (C, S)).astype(np.int64).copy()
    affs = np.broadcast_to(packed.spot_aff, (C, *packed.spot_aff.shape)).copy()

    # partial best-fit pass with gaps
    for c in range(C):
        for k in range(K):
            if not packed.slot_valid[c, k]:
                continue
            fits = fit_mask(
                np,
                free=frees[c],
                count=counts[c],
                max_pods=packed.spot_max_pods,
                node_taints=packed.spot_taints,
                node_ok=packed.spot_ok,
                node_aff=affs[c],
                req=packed.slot_req[c, k],
                tol=packed.slot_tol[c, k],
                aff=packed.slot_aff[c, k],
            )
            if not fits.any():
                continue  # leave the gap for repair
            slack = np.where(
                fits, frees[c, :, 0] - packed.slot_req[c, k, 0], np.inf
            )
            s = int(np.argmin(slack))
            assign[c, k] = s
            frees[c, s] -= packed.slot_req[c, k]
            counts[c, s] += 1
            affs[c, s] |= packed.slot_aff[c, k]

    for rnd in range(rounds):
        for c in range(C):
            unplaced = packed.slot_valid[c] & (assign[c] < 0)
            if not unplaced.any():
                continue
            p = int(np.argmax(unplaced))
            req_p = packed.slot_req[c, p]
            tol_p = packed.slot_tol[c, p]
            aff_p = packed.slot_aff[c, p]
            static_p = (
                np.all((packed.spot_taints & ~tol_p) == 0, axis=-1)
                & packed.spot_ok
            )
            unlock = np.zeros(K, bool)
            for k in range(K):
                s = assign[c, k]
                if s < 0:
                    continue
                if not static_p[s]:
                    continue
                if not np.all(
                    frees[c, s] + packed.slot_req[c, k] - req_p >= 0
                ):
                    continue
                unlock[k] = True
            n_unlock = int(unlock.sum())
            if not n_unlock:
                continue
            want = rnd % n_unlock
            q = int(np.flatnonzero(unlock)[want])
            sq = int(assign[c, q])
            # exact aff of q's node after q leaves (device lockstep):
            # static resident bits OR pods still assigned there
            aff_ej = np.asarray(packed.spot_aff[sq]).copy()
            for k in range(K):
                if k != q and assign[c, k] == sq:
                    aff_ej |= packed.slot_aff[c, k]
            if np.any(aff_p & aff_ej):
                continue  # rotation tries a different unlocker next round
            req_q = packed.slot_req[c, q]
            tol_q = packed.slot_tol[c, q]
            aff_q = packed.slot_aff[c, q]
            fits_q = fit_mask(
                np,
                free=frees[c],
                count=counts[c],
                max_pods=packed.spot_max_pods,
                node_taints=packed.spot_taints,
                node_ok=packed.spot_ok,
                node_aff=affs[c],
                req=req_q,
                tol=tol_q,
                aff=aff_q,
            )
            fits_q[sq] = False
            if fits_q.any():
                # depth-1 direct move: p -> s_q, q -> s2
                s2 = int(np.argmax(fits_q))
                assign[c, p] = sq
                assign[c, q] = s2
                frees[c, sq] += req_q - req_p
                frees[c, s2] -= req_q
                counts[c, s2] += 1
                affs[c, s2] |= aff_q
                affs[c, sq] = aff_ej | aff_p  # exact replacement, not OR
                continue
            if not chain:
                continue  # depth-1-only analyzer variant
            # depth-2 chain (device lockstep): q cannot re-place
            # directly; move it onto a third pod r's node and re-place
            # r elsewhere (p -> s_q, q -> s_r, r -> s3)
            static_q = (
                np.all((packed.spot_taints & ~tol_q) == 0, axis=-1)
                & packed.spot_ok
            )
            eligible = np.zeros(K, bool)
            for k in range(K):
                s = assign[c, k]
                if s < 0 or s == sq:
                    continue
                if not static_q[s]:
                    continue
                if not np.all(frees[c, s] + packed.slot_req[c, k] - req_q >= 0):
                    continue
                eligible[k] = True
            n_r = int(eligible.sum())
            if not n_r:
                continue
            # independent r rotation (device lockstep): see _repair_round
            r = int(np.flatnonzero(eligible)[(rnd // max(n_unlock, 1)) % n_r])
            sr = int(assign[c, r])
            fits_r = fit_mask(
                np,
                free=frees[c],
                count=counts[c],
                max_pods=packed.spot_max_pods,
                node_taints=packed.spot_taints,
                node_ok=packed.spot_ok,
                node_aff=affs[c],
                req=packed.slot_req[c, r],
                tol=packed.slot_tol[c, r],
                aff=packed.slot_aff[c, r],
            )
            fits_r[sr] = False
            fits_r[sq] = False
            if not fits_r.any():
                continue  # rotation elects a different r next round
            s3 = int(np.argmax(fits_r))
            aff_ej_r = np.asarray(packed.spot_aff[sr]).copy()
            for k in range(K):
                if k != r and assign[c, k] == sr:
                    aff_ej_r |= packed.slot_aff[c, k]
            if np.any(aff_q & aff_ej_r):
                continue
            assign[c, p] = sq
            assign[c, q] = sr
            assign[c, r] = s3
            frees[c, sq] += req_q - req_p
            frees[c, sr] += packed.slot_req[c, r] - req_q
            frees[c, s3] -= packed.slot_req[c, r]
            counts[c, s3] += 1
            affs[c, sq] = aff_ej | aff_p
            affs[c, sr] = aff_ej_r | aff_q
            affs[c, s3] |= packed.slot_aff[c, r]

    feasible = np.asarray(validate_assignment(np, packed, assign))
    assignment = np.where(feasible[:, None], assign, -1).astype(np.int32)
    return SolveResult(feasible=feasible, assignment=assignment)
