"""Device-side plan selection.

The control loop needs one thing from a solve: the *first feasible*
candidate in drain-priority order and its placement row (the reference
drains the first node whose ``canDrainNode`` succeeds, rescheduler.go:
228-287). Selecting on device and fetching a single small vector instead
of the full [C, K] assignment matrix keeps the host↔device boundary — the
framework's "device boundary" (SURVEY.md §3.3) — off the critical path:
on a latency-bound interconnect *every separate fetched array pays a full
round trip*, so the result is packed into ONE int32 vector.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Selection(NamedTuple):
    index: int  # first feasible candidate lane (drain-priority order)
    found: bool
    n_feasible: int
    row: np.ndarray  # int32 [K] spot assignment of that lane


def make_fused_planner(solve_fn):
    """Wrap a PackedCluster->SolveResult solver into a jitted function
    returning one int32 vector [idx, found, n_feasible, row...]; decode
    with ``decode_selection``."""

    @jax.jit
    def fused(packed):
        res = solve_fn(packed)
        feasible = res.feasible
        # candidates are pre-sorted least-requested-first, so argmax of the
        # boolean mask IS the reference's drain choice
        idx = jnp.argmax(feasible).astype(jnp.int32)
        return jnp.concatenate(
            [
                idx[None],
                jnp.any(feasible).astype(jnp.int32)[None],
                feasible.sum().astype(jnp.int32)[None],
                res.assignment[idx].astype(jnp.int32),
            ]
        )

    return fused


def decode_selection(vec) -> Selection:
    """One host fetch, then unpack."""
    vec = np.asarray(vec)
    return Selection(
        index=int(vec[0]),
        found=bool(vec[1]),
        n_feasible=int(vec[2]),
        row=vec[3:],
    )
