"""Device-side plan selection.

The control loop needs one thing from a solve: the *first feasible*
candidate in drain-priority order and its placement row (the reference
drains the first node whose ``canDrainNode`` succeeds, rescheduler.go:
228-287). Selecting on device and fetching a single small vector instead
of the full [C, K] assignment matrix keeps the host↔device boundary — the
framework's "device boundary" (SURVEY.md §3.3) — off the critical path:
on a latency-bound interconnect *every separate fetched array pays a full
round trip*, so the result is packed into ONE int32 vector.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Selection(NamedTuple):
    index: int  # first feasible candidate lane (drain-priority order)
    found: bool
    n_feasible: int
    row: np.ndarray  # int32 [K] spot assignment of that lane


def selection_vector(solve_fn, packed):
    """Solve + select, traced: the int32 vector [idx, found, n_feasible,
    row...] a single host fetch decodes (``decode_selection``). Shared
    by the in-process fused planner below and the multi-tenant batched
    program (parallel/tenant_batch.py), so the two paths cannot drift."""
    res = solve_fn(packed)
    feasible = res.feasible
    # candidates are pre-sorted least-requested-first, so argmax of the
    # boolean mask IS the reference's drain choice
    idx = jnp.argmax(feasible).astype(jnp.int32)
    return jnp.concatenate(
        [
            idx[None],
            jnp.any(feasible).astype(jnp.int32)[None],
            feasible.sum().astype(jnp.int32)[None],
            res.assignment[idx].astype(jnp.int32),
        ]
    )


def make_fused_planner(solve_fn):
    """Wrap a PackedCluster->SolveResult solver into a jitted function
    returning one int32 vector [idx, found, n_feasible, row...]; decode
    with ``decode_selection``."""

    @jax.jit
    def fused(packed):
        return selection_vector(solve_fn, packed)

    return fused


def decode_selection(vec) -> Selection:
    """One host fetch, then unpack."""
    vec = np.asarray(vec)
    return Selection(
        index=int(vec[0]),
        found=bool(vec[1]),
        n_feasible=int(vec[2]),
        row=vec[3:],
    )


class StagedStats(NamedTuple):
    """Staged-solve coverage bookkeeping for one tick."""

    chunks_solved: int
    chunks_skipped: int  # prefilter-eliminated + early-exit-bypassed
    lanes_eliminated: int  # prefilter verdicts, lane granularity
    count_truncated: bool  # early exit fired: n_feasible is a prefix count


class StagedPlanner:
    """Chunked, early-exiting selection over the candidate axis.

    The unstaged fused planner solves all C lanes even though the loop
    policy drains only the first feasible one. This planner walks the
    lanes *in selection order* in chunks of ``chunk_lanes``:

    - a chunk every lane of which the device prefilter
      (solver/prefilter.py) proves infeasible is skipped outright —
      exact, so its contribution to the feasible count is exactly 0;
    - remaining chunks are solved with the SAME union program the
      unstaged planner runs, sliced to the chunk's lanes (lanes are
      independent by construction — each is its own fork of the spot
      pool — so slicing cannot change any lane's verdict);
    - with ``early_exit`` (the production default), solving stops at the
      first chunk containing a feasible lane.

    Selection equivalence: (index, found, assignment row) are
    bit-identical to the unstaged fused planner always, and
    ``n_feasible`` is identical whenever no feasible lane exists or
    ``early_exit`` is off; when early exit fires, ``n_feasible`` is the
    exact count over the solved prefix (a lower bound) and
    ``StagedStats.count_truncated`` says so. ``tests/test_incremental.py``
    pins all of this against the unstaged planner.
    """

    def __init__(self, solve_fn, *, chunk_lanes: int = 256,
                 early_exit: bool = True):
        from k8s_spot_rescheduler_tpu.solver.prefilter import (
            lane_maybe_feasible,
        )

        self.chunk_lanes = int(chunk_lanes)
        self.early_exit = early_exit
        self._prefilter = jax.jit(lane_maybe_feasible)

        @functools.partial(jax.jit, static_argnames=("size",))
        def solve_chunk(packed, start, size):
            sub = packed._replace(
                slot_req=jax.lax.dynamic_slice_in_dim(
                    packed.slot_req, start, size
                ),
                slot_valid=jax.lax.dynamic_slice_in_dim(
                    packed.slot_valid, start, size
                ),
                slot_tol=jax.lax.dynamic_slice_in_dim(
                    packed.slot_tol, start, size
                ),
                slot_aff=jax.lax.dynamic_slice_in_dim(
                    packed.slot_aff, start, size
                ),
                cand_valid=jax.lax.dynamic_slice_in_dim(
                    packed.cand_valid, start, size
                ),
            )
            res = solve_fn(sub)
            idx = jnp.argmax(res.feasible).astype(jnp.int32)
            return jnp.concatenate(
                [
                    idx[None],
                    jnp.any(res.feasible).astype(jnp.int32)[None],
                    res.feasible.sum().astype(jnp.int32)[None],
                    res.assignment[idx].astype(jnp.int32),
                ]
            )

        self._solve_chunk = solve_chunk

    def dispatch_prefilter(self, packed):
        """Async-dispatch the per-lane bound; hand the result to
        ``start``/``solve`` so host work overlaps the device prefilter."""
        return self._prefilter(packed)

    def start(self, packed, maybe=None) -> dict:
        """Fetch the (tiny) prefilter verdict, decide the runnable chunk
        list and async-dispatch the first chunk — the device is already
        solving while the caller does host work before ``finish_run``."""
        import collections

        C = packed.slot_req.shape[0]
        if maybe is None:
            maybe = self.dispatch_prefilter(packed)
        maybe = np.asarray(maybe)  # C bools: the tick's only big fetch
        chunk = max(1, self.chunk_lanes)
        starts = list(range(0, C, chunk))
        run = {
            "packed": packed,
            "C": C,
            "K": packed.slot_req.shape[1],
            "runnable": [s for s in starts if maybe[s : s + chunk].any()],
            "n_chunks": len(starts),
            "eliminated": int((~maybe).sum()),
            "pending": collections.deque(),  # dispatched, not yet fetched
            "next": 0,
        }
        self._dispatch_next(run)
        return run

    def _dispatch_next(self, run) -> None:
        i = run["next"]
        if i < len(run["runnable"]):
            start = run["runnable"][i]
            size = min(max(1, self.chunk_lanes), run["C"] - start)
            run["pending"].append(
                (start, self._solve_chunk(run["packed"], start, size))
            )
            run["next"] = i + 1

    def finish_run(self, run):
        """Drain the chunk pipeline; returns (Selection, StagedStats).

        Chunks are fetched in selection order with pipeline depth 2 —
        chunk i+1 is dispatched before blocking on chunk i's fetch, so on
        a latency-bound link the round trips hide behind the next chunk's
        compute instead of serializing. Early exit costs at most the one
        speculatively-dispatched chunk."""
        fetched = 0
        n_feasible = 0
        found_idx = -1
        row = np.full(run["K"], -1, np.int32)
        while run["pending"]:
            self._dispatch_next(run)
            start, pending_vec = run["pending"].popleft()
            vec = np.asarray(pending_vec)
            fetched += 1
            n_feasible += int(vec[2])
            if found_idx < 0 and vec[1]:
                found_idx = start + int(vec[0])
                row = vec[3:]
                if self.early_exit:
                    break
        sel = Selection(
            index=found_idx if found_idx >= 0 else 0,
            found=found_idx >= 0,
            n_feasible=n_feasible,
            row=row,
        )
        stats = StagedStats(
            chunks_solved=fetched,
            chunks_skipped=run["n_chunks"] - fetched,
            lanes_eliminated=run["eliminated"],
            count_truncated=found_idx >= 0 and fetched < len(run["runnable"]),
        )
        return sel, stats

    def solve(self, packed, maybe=None):
        """Run the staged solve start-to-finish; returns
        (Selection, StagedStats)."""
        return self.finish_run(self.start(packed, maybe))

    __call__ = solve


def make_staged_planner(
    solve_fn, *, chunk_lanes: int = 256, early_exit: bool = True
) -> StagedPlanner:
    """Staged counterpart of ``make_fused_planner`` over the same
    PackedCluster->SolveResult solver."""
    return StagedPlanner(
        solve_fn, chunk_lanes=chunk_lanes, early_exit=early_exit
    )


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): the fused selection program and the staged
# chunk solver — the two jit roots the planner fetches from.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)


def _fused_union_build(s):
    from k8s_spot_rescheduler_tpu.solver.fallback import with_repair
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    return make_fused_planner(with_repair(plan_ffd, 8)), (packed_struct(s),)


def _staged_chunk_build(s):
    from k8s_spot_rescheduler_tpu.solver.fallback import with_repair
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    staged = StagedPlanner(with_repair(plan_ffd, 8), chunk_lanes=256)
    # start=0 traced; size is a static arg (the chunk ladder's compile
    # key) — make_jaxpr gets it via static_argnums
    return staged._solve_chunk, (packed_struct(s), 0, 256), (2,)


HOT_PROGRAMS = {
    "select.fused_union": HotProgram(
        build=_fused_union_build,
        covers=("solver.select:make_fused_planner.fused",),
    ),
    "select.staged_chunk": HotProgram(
        build=_staged_chunk_build,
        covers=("solver.select:StagedPlanner.__init__.solve_chunk",),
    ),
}
