"""Device-resident drain-to-exhaustion: one fetch, a whole schedule.

The full-scale consolidation sweep was the biggest wall-clock number in
the repo: 877 s to drain 856 nodes at config 3 (docs/RESULTS.md),
because every drain decision round-tripped the host↔device tunnel
(~65 ms RTT) while the device solve itself costs ~1.07 ms. The
chain-depth protocol (bench/protocol.py) already proved 50
data-dependent solves compose into one device program; this module is
the production version of that proof: a ``lax.while_loop`` that runs
the drain → commit → re-solve loop ON DEVICE —

- solve the current pack with the same union program the fused planner
  runs (first-fit ∪ best-fit ∪ repair, solver/fallback.py);
- elect the first feasible candidate in drain-priority order (the
  reference's loop policy, exactly ``solver/select.selection_vector``'s
  argmax);
- commit its evictees into the spot carry state (capacity depleted,
  pod counts bumped, resident anti-affinity words OR-ed — the same
  delta the scatter path applies between real ticks) and retire the
  drained lane from the candidate set;
- re-solve, until no candidate remains drainable or ``horizon`` steps
  are recorded —

and returns the whole drain *schedule* as ONE int32 matrix
``[horizon, 3 + K]`` (per step: ``idx | found | n_feasible | row``,
each row decoding exactly like ``solver/select.decode_selection``). The
host pays ONE fetch per ``horizon`` drains instead of one per drain.

Safety split (the proven-placement invariant is untouched): the device
schedule is a *prediction* under the quiescent-cluster assumption. The
execution layer (planner/schedule.py ``DrainSchedule``) re-packs the
live mirror before EVERY executed step, re-proves the step's placement
from scratch (solver/validate.py) against that live pack, and
invalidates the schedule tail on any churn — a schedule can save
fetches, never correctness.

``plan_schedule_oracle`` is the host-side twin (the same loop over
``solver/numpy_oracle.plan_union_oracle`` + ``commit_step_host``):
``solver="numpy"`` runs it, the planner service's host batch path runs
it per tenant, and tests pin the device matrix bit-identical to it.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster


class ScheduleStep(NamedTuple):
    """One decoded drain step: candidate lane + proven placement row in
    the schedule's OWN (base-pack) index space."""

    index: int
    n_feasible: int
    row: np.ndarray  # int32 [K]


def schedule_matrix(solve_fn, packed: PackedCluster, horizon: int):
    """Traced drain-to-exhaustion loop; returns int32 [horizon, 3+K].

    ``solve_fn`` is a PackedCluster -> SolveResult union program (the
    same one the fused planner wraps). The carry holds exactly the state
    a committed drain changes — spot capacity/count/affinity words and
    the candidate-validity mask — so each iteration re-solves the
    cluster the PREVIOUS drain left behind, all on device. The terminal
    probe (no candidate drainable) writes its ``found=0`` row too, so
    the matrix is self-delimiting."""
    import jax
    import jax.numpy as jnp

    C, K, _ = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    out0 = jnp.full((horizon, 3 + K), -1, jnp.int32)

    def cond(carry):
        step, done, _, _, _, _, _ = carry
        return (step < horizon) & ~done

    def body(carry):
        step, _, cand_valid, free, count, aff, out = carry
        cur = packed._replace(
            cand_valid=cand_valid,
            spot_free=free,
            spot_count=count,
            spot_aff=aff,
        )
        res = solve_fn(cur)
        feasible = res.feasible & cand_valid
        found = jnp.any(feasible)
        # candidates are pre-sorted least-requested-first: argmax of the
        # mask IS the reference's drain choice (select.selection_vector)
        idx = jnp.argmax(feasible).astype(jnp.int32)
        row = res.assignment[idx].astype(jnp.int32)  # [K]
        # commit (masked no-op when nothing was found): evictees deplete
        # spot capacity, bump pod counts, and land their anti-affinity
        # words on their nodes; the drained lane leaves the candidate
        # set (a drained-empty node packs cand_valid=False next tick)
        placed = (row >= 0) & packed.slot_valid[idx] & found  # [K]
        onehot = (jnp.arange(S, dtype=jnp.int32)[None, :] == row[:, None]) & (
            placed[:, None]
        )  # [K, S]
        free = free - jnp.einsum(
            "ks,kr->sr", onehot.astype(free.dtype), packed.slot_req[idx]
        )
        count = count + onehot.sum(axis=0).astype(count.dtype)
        contrib = jnp.where(
            onehot[:, :, None], packed.slot_aff[idx][:, None, :], jnp.uint32(0)
        )  # [K, S, A]
        aff = aff | jax.lax.reduce(
            contrib, jnp.uint32(0), jax.lax.bitwise_or, (0,)
        )
        cand_valid = cand_valid & ~(
            found & (jnp.arange(C, dtype=jnp.int32) == idx)
        )
        step_vec = jnp.concatenate(
            [
                jnp.where(found, idx, jnp.int32(-1))[None],
                found.astype(jnp.int32)[None],
                feasible.sum().astype(jnp.int32)[None],
                jnp.where(found, row, jnp.int32(-1)),
            ]
        )
        out = out.at[step].set(step_vec)
        return (step + jnp.int32(1), ~found, cand_valid, free, count, aff, out)

    init = (
        jnp.int32(0),
        jnp.asarray(False),
        jnp.asarray(packed.cand_valid),
        jnp.asarray(packed.spot_free),
        jnp.asarray(packed.spot_count).astype(jnp.int32),
        jnp.asarray(packed.spot_aff),
        out0,
    )
    final = jax.lax.while_loop(cond, body, init)
    return final[6]


def make_schedule_planner(solve_fn, horizon: int):
    """Jit-wrap ``schedule_matrix`` at a fixed ``horizon`` (the horizon
    is a compile-time shape decision — one compile per configured
    value, stable across ticks). The input tensors are NOT donated: the
    planner hands this program its device-resident cache, which must
    survive for the next tick's delta diff."""
    import jax

    @jax.jit
    def sched(packed):
        return schedule_matrix(solve_fn, packed, horizon)

    return sched


def decode_schedule(mat) -> List[ScheduleStep]:
    """The drain steps of one fetched schedule matrix, in execution
    order — the prefix of rows with ``found=1`` (the device loop stops
    at, and records, the first infeasible probe)."""
    mat = np.asarray(mat)
    steps: List[ScheduleStep] = []
    for r in range(mat.shape[0]):
        if mat[r, 1] != 1:
            break
        steps.append(
            ScheduleStep(
                index=int(mat[r, 0]),
                n_feasible=int(mat[r, 2]),
                row=np.asarray(mat[r, 3:], np.int32),
            )
        )
    return steps


def slice_lane(packed: PackedCluster, c: int) -> PackedCluster:
    """A single-lane view (C=1) of ``packed`` — lanes are independent
    fork copies, so slicing is exact. Shared by the schedule execution
    handle's per-step validation (planner/schedule.py) and the
    chain-depth analyzer (bench/chain_depth.py): one slicer, so a new
    lane-indexed PackedCluster field cannot be missed in one copy."""
    sl = slice(c, c + 1)
    return packed._replace(
        slot_req=packed.slot_req[sl],
        slot_valid=packed.slot_valid[sl],
        slot_tol=packed.slot_tol[sl],
        slot_aff=packed.slot_aff[sl],
        cand_valid=packed.cand_valid[sl],
    )


def commit_step_host(
    packed: PackedCluster, idx: int, row: np.ndarray
) -> PackedCluster:
    """Host twin of the device commit: apply one drain step's placements
    to the spot carry state and retire the drained lane. Exact in
    float32 (requests are scaled integers < 2**24), so a committed pack
    equals what a fresh pack of the post-drain cluster computes for the
    same fields."""
    free = np.array(packed.spot_free)
    count = np.array(packed.spot_count)
    aff = np.array(packed.spot_aff)
    cand = np.array(packed.cand_valid)
    row = np.asarray(row)
    for k in range(min(len(row), packed.slot_req.shape[1])):
        s = int(row[k])
        if s < 0 or not packed.slot_valid[idx, k]:
            continue
        free[s] -= packed.slot_req[idx, k]
        count[s] += 1
        aff[s] |= packed.slot_aff[idx, k]
    cand[idx] = False
    return packed._replace(
        spot_free=free, spot_count=count, spot_aff=aff, cand_valid=cand
    )


def plan_schedule_oracle(
    packed: PackedCluster,
    horizon: int,
    *,
    best_fit_fallback: bool = True,
    repair_rounds: int = 8,
) -> np.ndarray:
    """Host-side drain-to-exhaustion schedule: the same loop over the
    shared host union (solver/numpy_oracle.plan_union_oracle), emitting
    the identical int32 [horizon, 3+K] matrix. The device program is
    pinned bit-identical to this in tests/test_schedule.py."""
    from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_union_oracle

    C, K, _ = packed.slot_req.shape
    out = np.full((horizon, 3 + K), -1, np.int32)
    cur = packed
    for step in range(horizon):
        res = plan_union_oracle(
            cur,
            best_fit_fallback=best_fit_fallback,
            repair_rounds=repair_rounds,
        )
        feasible = np.asarray(res.feasible) & np.asarray(cur.cand_valid)
        out[step, 1] = 0
        out[step, 2] = int(feasible.sum())
        if not feasible.any():
            break
        idx = int(np.argmax(feasible))
        row = np.asarray(res.assignment[idx], np.int32)
        out[step, 0] = idx
        out[step, 1] = 1
        out[step, 3:] = row
        cur = commit_step_host(cur, idx, row)
    return out


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): the drain-to-exhaustion while-loop, traced at
# MAX_SHAPES with the full repair union in the body — the index-width
# pass vets the step/selection arithmetic and the dtype pass the carried
# spot state at the 20x target shapes like every other hot program.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)

SCHEDULE_PROBE_HORIZON = 32


def _schedule_build(s):
    from k8s_spot_rescheduler_tpu.solver.fallback import with_repair
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    return (
        make_schedule_planner(
            with_repair(plan_ffd, 8), SCHEDULE_PROBE_HORIZON
        ),
        (packed_struct(s),),
    )


HOT_PROGRAMS = {
    "schedule.drain_to_exhaustion": HotProgram(
        build=_schedule_build,
        covers=("solver.schedule:make_schedule_planner.sched",),
    ),
}
