"""Solver result container."""

from __future__ import annotations

from typing import NamedTuple


class SolveResult(NamedTuple):
    """Per-candidate drain feasibility.

    ``feasible[c]`` — every evictable pod of candidate c fits onto the spot
    pool (the reference's ``canDrainNode(...) == nil``).
    ``assignment[c, k]`` — spot index receiving slot k, -1 for unplaced or
    invalid slots. The reference discards placements after the feasibility
    proof (the real scheduler re-places evicted pods); we keep them for
    reporting and for the quality benchmarks.
    """

    feasible: "object"  # bool [C]
    assignment: "object"  # int32 [C, K]
