"""Serial first-fit oracle — the correctness anchor.

A direct, readable NumPy rendition of the reference's planning nest
(reference rescheduler.go:334-370):

- ``canDrainNode`` (355-370): walk the candidate's pods in order; every pod
  must land on some spot node or the whole candidate fails;
- ``findSpotNodeForPod`` (334-353): walk spot nodes in their static sorted
  order and return the first that passes the predicates;
- snapshot commit (366): a successful placement depletes that spot node's
  remaining capacity/count for subsequent pods of the *same* candidate;
- fork/revert (rescheduler.go:269-275): every candidate starts from the
  same initial spot pool — implemented here by copying the pool per lane.

The TPU solver (solver/ffd.py) must produce bit-identical feasibility and
assignments; the property tests enforce it.
"""

from __future__ import annotations

import numpy as np

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.predicates.masks import fit_mask
from k8s_spot_rescheduler_tpu.solver.result import SolveResult


def plan_oracle(packed: PackedCluster, best_fit: bool = False) -> SolveResult:
    """``best_fit=False`` is the reference's first-fit probe order;
    ``best_fit=True`` places each pod on the admissible node with the
    least remaining primary-resource slack (ties → probe order) — the
    fallback packing mode (solver/ffd.py ``plan_ffd``)."""
    C, K, _ = packed.slot_req.shape
    feasible = np.zeros(C, bool)
    assign = np.full((C, K), -1, np.int32)

    for c in range(C):
        if not packed.cand_valid[c]:
            continue
        # fork: private copy of the spot pool (rescheduler.go:269)
        free = packed.spot_free.copy()
        count = packed.spot_count.copy()
        aff = packed.spot_aff.copy()
        ok = True
        for k in range(K):
            if not packed.slot_valid[c, k]:
                continue
            fits = fit_mask(
                np,
                free=free,
                count=count,
                max_pods=packed.spot_max_pods,
                node_taints=packed.spot_taints,
                node_ok=packed.spot_ok,
                node_aff=aff,
                req=packed.slot_req[c, k],
                tol=packed.slot_tol[c, k],
                aff=packed.slot_aff[c, k],
            )
            if not fits.any():
                ok = False  # pod can't be rescheduled on any spot node
                break
            if best_fit:
                slack = free[:, 0] - packed.slot_req[c, k, 0]
                slack = np.where(fits, slack, np.inf)
                s = int(np.argmin(slack))  # tightest fit, ties → probe order
            else:
                s = int(np.argmax(fits))  # first fit in probe order
            assign[c, k] = s
            # commit into the fork (rescheduler.go:366)
            free[s] -= packed.slot_req[c, k]
            count[s] += 1
            aff[s] |= packed.slot_aff[c, k]
        feasible[c] = ok
        if not ok:
            assign[c] = -1  # revert (rescheduler.go:273)

    return SolveResult(feasible=feasible, assignment=assign)


def plan_union_oracle(
    packed: PackedCluster,
    *,
    best_fit_fallback: bool = True,
    repair_rounds: int = 0,
) -> SolveResult:
    """The host-side union composition — first-fit ∪ best-fit ∪ repair,
    mirroring the device path's ``lax.cond`` gating (solver/fallback.py:
    later passes are consumed only for lanes the earlier ones failed).
    The ONE host union: SolverPlanner's numpy branch and the planner
    service's host batch path both call this, so the two cannot drift."""
    result = plan_oracle(packed)
    if best_fit_fallback:
        bf = plan_oracle(packed, best_fit=True)
        result = SolveResult(
            feasible=result.feasible | bf.feasible,
            assignment=np.where(
                result.feasible[:, None], result.assignment, bf.assignment
            ),
        )
        need_repair = bool(
            np.any(np.asarray(packed.cand_valid) & ~result.feasible)
        )
        if repair_rounds > 0 and need_repair:
            from k8s_spot_rescheduler_tpu.solver.repair import (
                plan_repair_oracle,
            )

            rp = plan_repair_oracle(packed, rounds=repair_rounds)
            result = SolveResult(
                feasible=result.feasible | rp.feasible,
                assignment=np.where(
                    result.feasible[:, None], result.assignment, rp.assignment
                ),
            )
    return result
