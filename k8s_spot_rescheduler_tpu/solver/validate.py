"""From-scratch assignment validation — the repair phase's safety net.

The reference never needs this: its probe nest only ever commits
placements the predicate checker just approved (reference
rescheduler.go:344, 366). The repair solver (solver/repair.py) moves
already-placed pods around, so instead of trusting the search's
incremental bookkeeping, every lane's final assignment is re-proven
here against the ORIGINAL packed state: resources, pod counts, taints/
selector words, readiness, and pairwise anti-affinity. A lane that
fails any check reports infeasible — a search bug can lose a drain but
can never strand a pod (SURVEY.md §7 hard part (e): conservative in the
safe direction only).

``xp`` is ``numpy`` or ``jax.numpy`` — the device solver and the test
suite run the identical math.
"""

from __future__ import annotations

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster


def validate_assignment(xp, packed: PackedCluster, assign):
    """bool [C]: lane c's assignment row is a complete, predicate-valid
    placement of all its valid slots onto the spot pool.

    ``assign`` is int [C, K]; -1 = unplaced. Checks, all against the
    original (un-depleted) spot state:

    - completeness: every valid slot placed, every padding slot -1;
    - bounds: placements index real spot lanes;
    - capacity: per-node summed requests fit ``spot_free``;
    - pod count: ``spot_count`` + placements <= ``spot_max_pods``;
    - static admission: taint/selector/unplaceable words and ``spot_ok``
      per placed (slot, node) pair;
    - anti-affinity: no placed pair sharing a group bit co-locates, and
      no placed slot shares a bit with its node's existing pods.
    """
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    assign = xp.asarray(assign)
    valid = xp.asarray(packed.slot_valid)
    placed = assign >= 0

    complete = xp.all(placed == valid, axis=-1)  # [C]
    in_bounds = xp.all(xp.where(placed, assign < S, True), axis=-1)

    s_idx = xp.clip(assign, 0, S - 1).astype(xp.int32)
    onehot = (
        (s_idx[..., None] == xp.arange(S)) & (placed & valid)[..., None]
    )  # [C, K, S]
    onehot_f = onehot.astype(packed.slot_req.dtype)

    load = xp.einsum("cks,ckr->csr", onehot_f, xp.asarray(packed.slot_req))
    n_on = onehot.sum(axis=1)  # [C, S]
    # capacity binds only nodes that received placements: an untouched
    # node may legitimately carry negative free (over-committed in the
    # observed cluster) — placing on one is what's forbidden, matching
    # the greedy solvers' per-step ``free >= req`` gate
    used = n_on > 0
    res_ok = xp.all(
        (xp.asarray(packed.spot_free)[None] - load >= 0)
        | ~used[..., None],
        axis=(-2, -1),
    )  # [C]
    cnt_ok = xp.all(
        (
            xp.asarray(packed.spot_count)[None] + n_on
            <= xp.asarray(packed.spot_max_pods)[None]
        )
        | ~used,
        axis=-1,
    )

    # per-placement static admission word check
    taints = xp.asarray(packed.spot_taints)  # [S, W]
    node_words = taints[s_idx]  # [C, K, W] (gather)
    word_ok = xp.all(
        (node_words & ~xp.asarray(packed.slot_tol)) == 0, axis=-1
    )  # [C, K]
    ok_lane = xp.asarray(packed.spot_ok)[s_idx]  # [C, K]
    static_ok = xp.all(
        xp.where(placed & valid, word_ok & ok_lane, True), axis=-1
    )

    # anti-affinity: pairwise within a node + against the node's own mask.
    aff = xp.asarray(packed.slot_aff)  # [C, K, A] uint32
    live = placed & valid
    share = xp.any(aff[:, :, None, :] & aff[:, None, :, :], axis=-1)  # [C,K,K]
    same = (s_idx[:, :, None] == s_idx[:, None, :]) & (
        live[:, :, None] & live[:, None, :]
    )
    off_diag = ~xp.eye(K, dtype=bool)[None]
    pair_ok = ~xp.any(share & same & off_diag, axis=(-2, -1))
    node0 = xp.asarray(packed.spot_aff)[s_idx]  # [C, K, A]
    share0 = xp.any(aff & node0, axis=-1)  # [C, K]
    node_aff_ok = ~xp.any(share0 & live, axis=-1)

    return (
        xp.asarray(packed.cand_valid)
        & complete
        & in_bounds
        & res_ok
        & cnt_ok
        & static_ok
        & pair_ok
        & node_aff_ok
    )
