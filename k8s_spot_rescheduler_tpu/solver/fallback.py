"""Best-fit fallback composition.

The reference proves drain feasibility with pure first-fit over the
sorted spot pool (rescheduler.go:334-370) — fast but not the strongest
packing. The BASELINE.json north star asks for "first-fit-decreasing +
local-search": this module is that improvement phase. Candidates that
first-fit cannot prove get a second pass under best-fit-decreasing
(tightest primary-resource fit). Both passes produce predicate-valid
placements, so the union can only *add* drainable nodes over the
reference — quality strictly ≥, never an invalid drain.

First-fit assignments are preferred when both prove feasibility, keeping
the drain decision identical to the reference whenever the reference
could have made one.
"""

from __future__ import annotations

import jax.numpy as jnp

from k8s_spot_rescheduler_tpu.solver.result import SolveResult


def with_best_fit_fallback(solve_fn):
    """Wrap a solve(packed, best_fit=...) callable into one that unions
    first-fit and best-fit feasibility (one fused program under jit)."""

    def solve(packed) -> SolveResult:
        ff = solve_fn(packed)
        bf = solve_fn(packed, best_fit=True)
        feasible = ff.feasible | bf.feasible
        assignment = jnp.where(
            ff.feasible[:, None], ff.assignment, bf.assignment
        )
        return SolveResult(feasible=feasible, assignment=assignment)

    return solve


def with_repair(solve_fn, rounds: int):
    """First-fit ∪ best-fit ∪ bounded local-search repair
    (solver/repair.py), still one fused device program.

    Preference order keeps the drain decision identical to the
    reference whenever the reference could have made one: a lane's
    first-fit placement wins when first-fit proves it, then best-fit,
    then the repaired assignment. Repair placements are re-proven from
    scratch (solver/validate.py), so the union can only add drainable
    nodes — never an invalid drain.

    Repair results are only ever CONSUMED for lanes both greedy passes
    failed, so the whole repair phase (partial pass + rounds + revalidate
    — measured ~60 ms device time at config-3 scale vs ~2 ms for the
    greedy scans) runs under ``lax.cond``: a tick where greedy proves
    every valid lane — the common, uncontended case — skips it entirely
    at runtime. Identical results either way."""
    import jax

    from k8s_spot_rescheduler_tpu.solver.repair import plan_repair

    def solve(packed) -> SolveResult:
        ff = solve_fn(packed)
        bf = solve_fn(packed, best_fit=True)
        greedy_feasible = ff.feasible | bf.feasible
        need_repair = jnp.any(
            jnp.asarray(packed.cand_valid) & ~greedy_feasible
        )
        rp = jax.lax.cond(
            need_repair,
            lambda p: plan_repair(p, rounds=rounds),
            lambda p: SolveResult(
                feasible=jnp.zeros_like(greedy_feasible),
                assignment=jnp.full_like(ff.assignment, -1),
            ),
            packed,
        )
        feasible = greedy_feasible | rp.feasible
        assignment = jnp.where(
            ff.feasible[:, None],
            ff.assignment,
            jnp.where(bf.feasible[:, None], bf.assignment, rp.assignment),
        )
        return SolveResult(feasible=feasible, assignment=assignment)

    return solve
