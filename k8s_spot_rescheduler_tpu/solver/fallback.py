"""Best-fit fallback composition.

The reference proves drain feasibility with pure first-fit over the
sorted spot pool (rescheduler.go:334-370) — fast but not the strongest
packing. The BASELINE.json north star asks for "first-fit-decreasing +
local-search": this module is that improvement phase. Candidates that
first-fit cannot prove get a second pass under best-fit-decreasing
(tightest primary-resource fit). Both passes produce predicate-valid
placements, so the union can only *add* drainable nodes over the
reference — quality strictly ≥, never an invalid drain.

First-fit assignments are preferred when both prove feasibility, keeping
the drain decision identical to the reference whenever the reference
could have made one.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from k8s_spot_rescheduler_tpu.solver.result import SolveResult


def _cond_solve(need, solve_thunk, like: SolveResult) -> SolveResult:
    """Run ``solve_thunk`` under ``lax.cond``: improvement passes
    (best-fit, repair) are only CONSUMED for lanes the preceding pass
    failed, so a tick where everything already proved skips their cost
    at runtime — identical results either way."""
    return jax.lax.cond(
        need,
        solve_thunk,
        lambda: SolveResult(
            feasible=jnp.zeros_like(like.feasible),
            assignment=jnp.full_like(like.assignment, -1),
        ),
    )


def with_best_fit_fallback(solve_fn):
    """Wrap a solve(packed, best_fit=...) callable into one that unions
    first-fit and best-fit feasibility (one fused program under jit).
    Best-fit only runs when first-fit left a valid lane unproven."""

    def solve(packed) -> SolveResult:
        ff = solve_fn(packed)
        need = jnp.any(jnp.asarray(packed.cand_valid) & ~ff.feasible)
        bf = _cond_solve(need, lambda: solve_fn(packed, best_fit=True), ff)
        feasible = ff.feasible | bf.feasible
        assignment = jnp.where(
            ff.feasible[:, None], ff.assignment, bf.assignment
        )
        return SolveResult(feasible=feasible, assignment=assignment)

    return solve


def with_repair(solve_fn, rounds: int, spot_chunks: int = 1):
    """First-fit ∪ best-fit ∪ bounded local-search repair
    (solver/repair.py), still one fused device program.
    ``spot_chunks`` > 1 swaps in the elect-then-commit spot-chunked
    search (``plan_repair_chunked``, bit-identical results) whose
    per-round working set is O(S / spot_chunks) — how the cand-only
    sharding tier keeps repair past its unchunked ceiling
    (solver/memory.pick_repair_chunks decides the count).

    Preference order keeps the drain decision identical to the
    reference whenever the reference could have made one: a lane's
    first-fit placement wins when first-fit proves it, then best-fit,
    then the repaired assignment. Repair placements are re-proven from
    scratch (solver/validate.py), so the union can only add drainable
    nodes — never an invalid drain.

    Each improvement pass is only ever CONSUMED for lanes the passes
    before it failed, so best-fit AND the repair phase (partial pass +
    rounds + revalidate — measured ~60 ms device time at config-3 scale
    vs ~2 ms for the first-fit scan) run under ``lax.cond``: a tick
    where first-fit proves every valid lane — the common, uncontended
    case — skips both entirely at runtime. Identical results either
    way."""
    from k8s_spot_rescheduler_tpu.solver.repair import (
        plan_repair,
        plan_repair_chunked,
    )

    if spot_chunks > 1:
        def repair_thunk(packed):
            return plan_repair_chunked(
                packed, rounds=rounds, spot_chunks=spot_chunks
            )
    else:
        def repair_thunk(packed):
            return plan_repair(packed, rounds=rounds)

    def solve(packed) -> SolveResult:
        cand_valid = jnp.asarray(packed.cand_valid)
        ff = solve_fn(packed)
        need_bf = jnp.any(cand_valid & ~ff.feasible)
        bf = _cond_solve(need_bf, lambda: solve_fn(packed, best_fit=True), ff)
        greedy_feasible = ff.feasible | bf.feasible
        need_repair = jnp.any(cand_valid & ~greedy_feasible)
        rp = _cond_solve(need_repair, lambda: repair_thunk(packed), ff)
        feasible = greedy_feasible | rp.feasible
        assignment = jnp.where(
            ff.feasible[:, None],
            ff.assignment,
            jnp.where(bf.feasible[:, None], bf.assignment, rp.assignment),
        )
        return SolveResult(feasible=feasible, assignment=assignment)

    return solve


def with_repair_streamed(
    rounds: int,
    carry_chunks: int,
    layout,
    chain: bool = True,
    best_fit_fallback: bool = True,
    use_pallas: bool = False,
):
    """The carry-streamed union (ROADMAP 5): first-fit with the spot
    axis STREAMED in ``carry_chunks`` ordered chunks (leftovers flow
    forward — resident first-fit carry O(S / carry_chunks)), best-fit
    as per-slot elect-then-commit over the stacked narrow chunk state,
    and the spot-chunked repair rounds — every pass on the DELTA-form
    narrow carry ``layout`` (solver/carry.py) widened on read, so the
    whole union is bit-identical to ``with_repair(plan_ffd, rounds)``
    while the resident per-(lane, spot) carry bytes shrink ~2x and the
    per-round temporaries shrink by the chunk count. This is the tier
    ``planner/solver_planner._maybe_shard`` dispatches above the 2-D
    fallback: repair stays LIVE past the wide layouts' carry bound.

    ``use_pallas`` swaps the best-fit pass's XLA elect-then-commit scan
    for the fused Pallas stream kernel
    (``ops/pallas_ffd.plan_stream_bf_pallas`` — bit-identical by the
    chunk-election-is-global-argmin property, narrow carry resident in
    VMEM); first-fit and repair are unchanged.

    Same cond discipline as ``with_repair``: best-fit and repair only
    execute when the pass before them left a valid lane unproven."""
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd_streamed
    from k8s_spot_rescheduler_tpu.solver.repair import plan_repair_chunked

    if use_pallas:
        from k8s_spot_rescheduler_tpu.ops.pallas_ffd import (
            plan_stream_bf_pallas,
        )

        def bf_thunk(packed):
            return plan_stream_bf_pallas(
                packed, carry_chunks=carry_chunks, layout=layout
            )
    else:
        def bf_thunk(packed):
            return plan_ffd_streamed(
                packed,
                carry_chunks=carry_chunks,
                layout=layout,
                best_fit=True,
            )

    def solve(packed) -> SolveResult:
        cand_valid = jnp.asarray(packed.cand_valid)
        ff = plan_ffd_streamed(
            packed, carry_chunks=carry_chunks, layout=layout
        )
        if not best_fit_fallback:
            return ff
        need_bf = jnp.any(cand_valid & ~ff.feasible)
        bf = _cond_solve(need_bf, lambda: bf_thunk(packed), ff)
        greedy_feasible = ff.feasible | bf.feasible
        if rounds <= 0:
            assignment = jnp.where(
                ff.feasible[:, None], ff.assignment, bf.assignment
            )
            return SolveResult(
                feasible=greedy_feasible, assignment=assignment
            )
        need_repair = jnp.any(cand_valid & ~greedy_feasible)
        rp = _cond_solve(
            need_repair,
            lambda: plan_repair_chunked(
                packed,
                rounds=rounds,
                chain=chain,
                spot_chunks=carry_chunks,
                layout=layout,
            ),
            ff,
        )
        feasible = greedy_feasible | rp.feasible
        assignment = jnp.where(
            ff.feasible[:, None],
            ff.assignment,
            jnp.where(bf.feasible[:, None], bf.assignment, rp.assignment),
        )
        return SolveResult(feasible=feasible, assignment=assignment)

    return solve


def union_program(
    rounds: int,
    best_fit_fallback: bool = True,
    *,
    repair_spot_chunks: int = 1,
    carry_chunks: int = 0,
    carry_layout=None,
    use_pallas: bool = False,
):
    """THE union-composition ladder every dispatch site builds from —
    the cand-sharded block program (parallel/sharded_ffd) and the
    batched tenant program (parallel/tenant_batch) call this one
    helper, so their compositions can never drift. ``carry_chunks`` >=
    1 selects the carry-streamed narrow union (``carry_layout``
    defaults to NARROW_LAYOUT; ``use_pallas`` swaps its best-fit pass
    for the fused Pallas stream kernel); otherwise first-fit ∪
    best-fit ∪ (spot-chunked) repair per the flags."""
    from k8s_spot_rescheduler_tpu.solver.carry import NARROW_LAYOUT
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    if carry_chunks and carry_chunks >= 1:
        return with_repair_streamed(
            rounds,
            carry_chunks,
            carry_layout if carry_layout is not None else NARROW_LAYOUT,
            best_fit_fallback=best_fit_fallback,
            use_pallas=use_pallas,
        )
    if best_fit_fallback and rounds > 0:
        return with_repair(plan_ffd, rounds, spot_chunks=repair_spot_chunks)
    if best_fit_fallback:
        return with_best_fit_fallback(plan_ffd)
    return plan_ffd


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): the fused union compositions the planner
# actually runs. The ``reconcile`` specs tie each composition to
# solver/memory.estimate_union_hbm_breakdown at the matching
# repair_spot_chunks mode — the memory-reconcile pass diffs the traced
# program's live-buffer model against the estimate so the HBM dispatch
# (pick_repair_chunks / should_shard) can't rot as kernels change. The
# streamed entry reconciles against the NARROW-layout carry estimate
# (carry_chunks mode) — the ROADMAP-5 regression gate.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)


def _union_greedy_build(s):
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    return with_best_fit_fallback(plan_ffd), (packed_struct(s),)


def _union_repair_build(s, spot_chunks=1):
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    return (
        with_repair(plan_ffd, rounds=8, spot_chunks=spot_chunks),
        (packed_struct(s),),
    )


HOT_PROGRAMS = {
    "union.greedy": HotProgram(
        build=_union_greedy_build,
        covers=("solver.ffd:plan_ffd",),
        reconcile={"repair_spot_chunks": 0},
    ),
    "union.repair": HotProgram(
        build=_union_repair_build,
        covers=("solver.repair:plan_repair",),
        reconcile={"repair_spot_chunks": 1},
    ),
    "union.repair_chunked": HotProgram(
        build=lambda s: _union_repair_build(s, spot_chunks=4),
        covers=("solver.repair:plan_repair_chunked",),
        reconcile={"repair_spot_chunks": 4},
    ),
    "union.repair_streamed": HotProgram(
        build=lambda s: (
            with_repair_streamed(8, 4, _narrow_layout()),
            (packed_struct(s),),
        ),
        covers=(
            "solver.ffd:plan_ffd_streamed",
            "solver.repair:plan_repair_chunked",
        ),
        reconcile={"repair_spot_chunks": 4, "carry_chunks": 4},
    ),
}


def _narrow_layout():
    from k8s_spot_rescheduler_tpu.solver.carry import NARROW_LAYOUT

    return NARROW_LAYOUT
