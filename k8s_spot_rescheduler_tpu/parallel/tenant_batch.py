"""Multi-tenant batched selection: a fleet of clusters in one solve.

The cand-sharded tier (parallel/sharded_ffd.plan_union_cand_sharded)
proves candidate lanes solve with zero cross-lane collectives — lanes
are Fork/Revert forks and never interact. Tenants (whole clusters) are
one level coarser: not only do their lanes not interact, they do not
even share a spot pool. So a fleet's concurrent plan requests, padded to
one shape bucket (service/buckets.py), stack along a new leading tenant
axis and solve as ONE device program:

- each tenant's problem runs the COMPLETE single-chip union program
  (first-fit ∪ best-fit ∪ repair — the same ``solve`` composition
  SolverPlanner builds, so a batched tenant's selection is bit-identical
  to its solo in-process plan, pinned by ``make serve-smoke``);
- selection happens on device per tenant (solver/select.selection_vector)
  and the host fetches one [T, 3+K] int32 matrix — a few hundred bytes
  per tenant, the same boundary discipline as the in-process planner;
- on a multi-device mesh the tenant axis shards over the devices
  (parallel/mesh.make_tenant_mesh) with everything else local: zero
  collectives, embarrassing parallelism at cluster granularity. On one
  device (or a tenant count the mesh does not divide) the batch runs as
  a plain ``vmap`` — same program, same results.

This is ROADMAP item 2's kernel: the device-only solve is ~1 ms/tick
and a tick is seconds long, so one TPU that solves T tenants per batch
serves T clusters at the cost the reference pays for one.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, PartitionSpec as P

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.parallel.mesh import TENANT_AXIS
# the jax>=0.6 / experimental shard_map version shim lives beside the
# other mesh programs — one shim, every sharded path
from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import shard_map
from k8s_spot_rescheduler_tpu.solver.select import selection_vector


def plan_tenants_batched(
    mesh: Mesh | None,
    stacked: PackedCluster,
    *,
    rounds: int = 0,
    best_fit_fallback: bool = True,
):
    """Solve T stacked tenant problems; returns int32 [T, 3 + K].

    ``stacked`` is a PackedCluster whose every field carries a leading
    tenant axis (service/buckets.stack_bucket). Row t decodes with
    ``solver/select.decode_selection`` exactly as a solo solve would.
    """
    from k8s_spot_rescheduler_tpu.solver.fallback import (
        with_best_fit_fallback,
        with_repair,
    )
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    if best_fit_fallback and rounds > 0:
        solve = with_repair(plan_ffd, rounds)
    elif best_fit_fallback:
        solve = with_best_fit_fallback(plan_ffd)
    else:
        solve = plan_ffd

    def tenant_select(p):
        return selection_vector(solve, p)

    T = stacked.slot_req.shape[0]
    n = mesh.devices.size if mesh is not None else 1
    if n <= 1 or T % n != 0:
        # single device, or a tenant count the mesh does not divide
        # evenly. PlannerService._solve pads every mesh batch's tenant
        # axis to a device multiple with all-invalid problems, so with
        # a mesh in play this branch never runs in the service — it is
        # the CPU/1-chip path and the direct-caller fallback.
        return jax.vmap(tenant_select)(stacked)
    specs = PackedCluster(*(P(TENANT_AXIS) for _ in PackedCluster._fields))

    def local(block):
        # one device's tenant block, vmapped — no collectives at all
        return jax.vmap(tenant_select)(block)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=P(TENANT_AXIS),
        check_vma=False,
    )
    return fn(stacked)


def plan_tenants_scheduled(
    stacked: PackedCluster,
    *,
    horizon: int,
    rounds: int = 0,
    best_fit_fallback: bool = True,
):
    """Solve T stacked tenant problems to whole DRAIN SCHEDULES;
    returns int32 [T, horizon, 3 + K].

    The drain-to-exhaustion while-loop (solver/schedule.py) vmaps over
    the tenant axis exactly like the single-plan program: tenants never
    interact, so under vmap the loop runs until the LAST tenant
    exhausts with the finished tenants' lanes masked no-ops. Schedule
    batches are rare by construction (one per ``horizon`` drains per
    tenant), so this first version stays single-device vmap — the
    tenant-mesh sharding the single-plan batch uses is future work."""
    from k8s_spot_rescheduler_tpu.solver.fallback import (
        with_best_fit_fallback,
        with_repair,
    )
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd
    from k8s_spot_rescheduler_tpu.solver.schedule import schedule_matrix

    if best_fit_fallback and rounds > 0:
        solve = with_repair(plan_ffd, rounds)
    elif best_fit_fallback:
        solve = with_best_fit_fallback(plan_ffd)
    else:
        solve = plan_ffd

    def tenant_sched(p):
        return schedule_matrix(solve, p, horizon)

    return jax.vmap(tenant_sched)(stacked)


def make_tenant_schedule_planner(
    *,
    horizon: int,
    rounds: int = 0,
    best_fit_fallback: bool = True,
):
    """The service's jitted batched-schedule program (one per horizon —
    the horizon is the compile key, stable per fleet config)."""
    return jax.jit(
        functools.partial(
            plan_tenants_scheduled,
            horizon=horizon,
            rounds=rounds,
            best_fit_fallback=best_fit_fallback,
        )
    )


def make_tenant_batch_planner(
    mesh: Mesh | None = None,
    *,
    rounds: int = 0,
    best_fit_fallback: bool = True,
):
    """The service's jitted batch program. One returned callable serves
    every bucket: jit re-specializes per stacked shape, and the bucket
    discipline (powers of two per axis) bounds the distinct shapes to
    O(log C · log K · log S) for the fleet's lifetime."""
    return jax.jit(
        functools.partial(
            plan_tenants_batched,
            mesh,
            rounds=rounds,
            best_fit_fallback=best_fit_fallback,
        )
    )


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): the batched tenant program, traced at the
# declared max shapes with an 8-tenant stack over the tenant mesh (the
# audit env exposes 8 virtual CPU devices), so the index-width and
# dtype passes see the exact program the service dispatches.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)

TENANT_PROBE_COUNT = 8


def _tenant_batch_build(s):
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_tenant_mesh

    base = packed_struct(s)
    stacked = PackedCluster(
        *(
            jax.ShapeDtypeStruct((TENANT_PROBE_COUNT,) + f.shape, f.dtype)
            for f in base
        )
    )
    return (
        functools.partial(
            plan_tenants_batched, make_tenant_mesh(), rounds=8
        ),
        (stacked,),
    )


def _tenant_schedule_build(s):
    base = packed_struct(s)
    stacked = PackedCluster(
        *(
            jax.ShapeDtypeStruct((TENANT_PROBE_COUNT,) + f.shape, f.dtype)
            for f in base
        )
    )
    return (
        functools.partial(plan_tenants_scheduled, horizon=8, rounds=8),
        (stacked,),
    )


HOT_PROGRAMS = {
    "service.tenant_batch": HotProgram(
        build=_tenant_batch_build,
        covers=(
            "parallel.tenant_batch:plan_tenants_batched",
            "parallel.tenant_batch:plan_tenants_batched.local",
        ),
    ),
    "service.tenant_schedule": HotProgram(
        build=_tenant_schedule_build,
        covers=("parallel.tenant_batch:plan_tenants_scheduled",),
    ),
}
