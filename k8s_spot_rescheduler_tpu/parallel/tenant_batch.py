"""Multi-tenant batched selection: a fleet of clusters in one solve.

The cand-sharded tier (parallel/sharded_ffd.plan_union_cand_sharded)
proves candidate lanes solve with zero cross-lane collectives — lanes
are Fork/Revert forks and never interact. Tenants (whole clusters) are
one level coarser: not only do their lanes not interact, they do not
even share a spot pool. So a fleet's concurrent plan requests, padded to
one shape bucket (service/buckets.py), stack along a new leading tenant
axis and solve as ONE device program:

- each tenant's problem runs the COMPLETE single-chip union program
  (first-fit ∪ best-fit ∪ repair — the same ``solve`` composition
  SolverPlanner builds, so a batched tenant's selection is bit-identical
  to its solo in-process plan, pinned by ``make serve-smoke``);
- selection happens on device per tenant (solver/select.selection_vector)
  and the host fetches one [T, 3+K] int32 matrix — a few hundred bytes
  per tenant, the same boundary discipline as the in-process planner;
- on a multi-device mesh the tenant axis shards over the devices
  (parallel/mesh.make_tenant_mesh) with everything else local: zero
  collectives, embarrassing parallelism at cluster granularity. On one
  device (or a tenant count the mesh does not divide) the batch runs as
  a plain ``vmap`` — same program, same results.

This is ROADMAP item 2's kernel: the device-only solve is ~1 ms/tick
and a tick is seconds long, so one TPU that solves T tenants per batch
serves T clusters at the cost the reference pays for one.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, PartitionSpec as P

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.parallel.mesh import TENANT_AXIS
# the jax>=0.6 / experimental shard_map version shim lives beside the
# other mesh programs — one shim, every sharded path
from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import shard_map
from k8s_spot_rescheduler_tpu.solver.select import selection_vector


def _tenant_union(rounds, best_fit_fallback, carry_chunks, carry_layout):
    """The per-tenant union program the batch vmaps — the ONE
    composition ladder of solver/fallback.union_program, so the
    service's program can never drift from the cand-sharded planner's
    (``carry_chunks`` >= 1 gives huge-bucket tenants the ROADMAP-5
    narrow delta-carry streamed union under vmap too)."""
    from k8s_spot_rescheduler_tpu.solver.fallback import union_program

    return union_program(
        rounds,
        best_fit_fallback,
        carry_chunks=carry_chunks,
        carry_layout=carry_layout,
    )


def plan_tenants_batched(
    mesh: Mesh | None,
    stacked: PackedCluster,
    *,
    rounds: int = 0,
    best_fit_fallback: bool = True,
    carry_chunks: int = 0,
    carry_layout=None,
):
    """Solve T stacked tenant problems; returns int32 [T, 3 + K].

    ``stacked`` is a PackedCluster whose every field carries a leading
    tenant axis (service/buckets.stack_bucket). Row t decodes with
    ``solver/select.decode_selection`` exactly as a solo solve would.
    """
    solve = _tenant_union(rounds, best_fit_fallback, carry_chunks, carry_layout)

    def tenant_select(p):
        return selection_vector(solve, p)

    T = stacked.slot_req.shape[0]
    n = mesh.devices.size if mesh is not None else 1
    if n <= 1 or T % n != 0:
        # single device, or a tenant count the mesh does not divide
        # evenly. PlannerService._solve pads every mesh batch's tenant
        # axis to a device multiple with all-invalid problems, so with
        # a mesh in play this branch never runs in the service — it is
        # the CPU/1-chip path and the direct-caller fallback.
        return jax.vmap(tenant_select)(stacked)
    specs = PackedCluster(*(P(TENANT_AXIS) for _ in PackedCluster._fields))

    def local(block):
        # one device's tenant block, vmapped — no collectives at all
        return jax.vmap(tenant_select)(block)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=P(TENANT_AXIS),
        check_vma=False,
    )
    return fn(stacked)


def plan_tenants_scheduled(
    mesh: Mesh | None,
    stacked: PackedCluster,
    *,
    horizon: int,
    rounds: int = 0,
    best_fit_fallback: bool = True,
):
    """Solve T stacked tenant problems to whole DRAIN SCHEDULES;
    returns int32 [T, horizon, 3 + K].

    The drain-to-exhaustion while-loop (solver/schedule.py) vmaps over
    the tenant axis exactly like the single-plan program: tenants never
    interact, so under vmap the loop runs until the LAST tenant
    exhausts with the finished tenants' lanes masked no-ops. On a
    multi-device mesh the tenant axis shards over the devices exactly
    like the single-plan batch (zero collectives — each device runs
    its block's while-loop independently, so the wall clock is the
    slowest BLOCK, not the slowest tenant times T); the service pads
    the tenant axis to a device multiple with all-invalid problems,
    the same inert padding the single-plan batch uses."""
    from k8s_spot_rescheduler_tpu.solver.fallback import union_program
    from k8s_spot_rescheduler_tpu.solver.schedule import schedule_matrix

    solve = union_program(rounds, best_fit_fallback)

    def tenant_sched(p):
        return schedule_matrix(solve, p, horizon)

    T = stacked.slot_req.shape[0]
    n = mesh.devices.size if mesh is not None else 1
    if n <= 1 or T % n != 0:
        # single device, or a tenant count the mesh does not divide:
        # same contract as plan_tenants_batched — the service pads to
        # a multiple, so with a mesh in play this is the 1-chip path
        return jax.vmap(tenant_sched)(stacked)
    specs = PackedCluster(*(P(TENANT_AXIS) for _ in PackedCluster._fields))

    def local(block):
        return jax.vmap(tenant_sched)(block)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=P(TENANT_AXIS),
        check_vma=False,
    )
    return fn(stacked)


def make_tenant_schedule_planner(
    mesh: Mesh | None = None,
    *,
    horizon: int,
    rounds: int = 0,
    best_fit_fallback: bool = True,
):
    """The service's jitted batched-schedule program (one per horizon —
    the horizon is the compile key, stable per fleet config)."""
    return jax.jit(
        functools.partial(
            plan_tenants_scheduled,
            mesh,
            horizon=horizon,
            rounds=rounds,
            best_fit_fallback=best_fit_fallback,
        )
    )


def apply_tenant_deltas(
    slot_req, slot_valid, slot_tol, slot_aff, cand_valid,
    spot_free, spot_count, spot_max_pods, spot_taints, spot_ok, spot_aff,
    deltas,
):
    """Scatter T tenants' wire deltas into their stacked cached states
    in ONE device program — the batched twin of the in-process donated
    scatter (planner/solver_planner._delta_apply_fn): every argument
    carries a leading tenant axis ([T, C, ...] states, [T, rows, ...]
    padded delta sections from models/columnar.pad_packed_delta), the
    scatter vmaps over it, and index pads point one past the axis end
    so ``mode="drop"`` makes them no-ops (a full-pack tenant rides a
    mixed batch with an all-pad empty delta). The 11 state tensors are
    donated by the jit wrapper (the scatter aliases them instead of
    allocating a second batch-state), so steady-state HOST→DEVICE
    upload traffic is the deltas alone — batch assembly still restacks
    the cached per-tenant twins along the tenant axis, a device-side
    copy of the same order the batch solve already pays reading its
    inputs."""

    def one(
        s_req, s_valid, s_tol, s_aff, c_valid,
        p_free, p_count, p_max, p_taints, p_ok, p_aff, d,
    ):
        return PackedCluster(
            slot_req=s_req.at[d.lanes].set(d.lane_slot_req, mode="drop"),
            slot_valid=s_valid.at[d.lanes].set(
                d.lane_slot_valid, mode="drop"
            ),
            slot_tol=s_tol.at[d.lanes].set(d.lane_slot_tol, mode="drop"),
            slot_aff=s_aff.at[d.lanes].set(d.lane_slot_aff, mode="drop"),
            cand_valid=c_valid.at[d.cand_rows].set(
                d.cand_valid, mode="drop"
            ),
            spot_free=p_free.at[d.spot_rows].set(d.spot_free, mode="drop"),
            spot_count=p_count.at[d.spot_rows].set(
                d.spot_count, mode="drop"
            ),
            spot_max_pods=p_max.at[d.spot_rows].set(
                d.spot_max_pods, mode="drop"
            ),
            spot_taints=p_taints.at[d.spot_rows].set(
                d.spot_taints, mode="drop"
            ),
            spot_ok=p_ok.at[d.spot_rows].set(d.spot_ok, mode="drop"),
            spot_aff=p_aff.at[d.spot_rows].set(d.spot_aff, mode="drop"),
        )

    return jax.vmap(one)(
        slot_req, slot_valid, slot_tol, slot_aff, cand_valid,
        spot_free, spot_count, spot_max_pods, spot_taints, spot_ok,
        spot_aff, deltas,
    )


def make_tenant_delta_applier():
    """The service's jitted batched delta scatter: the 11 stacked state
    tensors are donated (the update aliases them in place in device
    memory — audited by the transfer pass like the in-process scatter's
    11 donations), re-specialized per (T, rows) shape with both axes on
    power-of-two ladders so compiles stay O(log T · log churn)."""
    return jax.jit(
        apply_tenant_deltas, donate_argnums=tuple(range(11))
    )


def make_tenant_batch_planner(
    mesh: Mesh | None = None,
    *,
    rounds: int = 0,
    best_fit_fallback: bool = True,
    carry_chunks: int = 0,
    carry_layout=None,
):
    """The service's jitted batch program. One returned callable serves
    every bucket: jit re-specializes per stacked shape, and the bucket
    discipline (powers of two per axis) bounds the distinct shapes to
    O(log C · log K · log S) for the fleet's lifetime. ``carry_chunks``
    >= 1 runs every tenant on the carry-streamed narrow union (same
    selections, narrower resident carries — for buckets whose stacked
    wide state would not fit the device)."""
    return jax.jit(
        functools.partial(
            plan_tenants_batched,
            mesh,
            rounds=rounds,
            best_fit_fallback=best_fit_fallback,
            carry_chunks=carry_chunks,
            carry_layout=carry_layout,
        )
    )


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): the batched tenant program, traced at the
# declared max shapes with an 8-tenant stack over the tenant mesh (the
# audit env exposes 8 virtual CPU devices), so the index-width and
# dtype passes see the exact program the service dispatches.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)

TENANT_PROBE_COUNT = 8


def _tenant_batch_build(s):
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_tenant_mesh

    base = packed_struct(s)
    stacked = PackedCluster(
        *(
            jax.ShapeDtypeStruct((TENANT_PROBE_COUNT,) + f.shape, f.dtype)
            for f in base
        )
    )
    return (
        functools.partial(
            plan_tenants_batched, make_tenant_mesh(), rounds=8
        ),
        (stacked,),
    )


def _tenant_batch_carry_build(s):
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_tenant_mesh
    from k8s_spot_rescheduler_tpu.solver.carry import NARROW_LAYOUT

    base = packed_struct(s)
    stacked = PackedCluster(
        *(
            jax.ShapeDtypeStruct((TENANT_PROBE_COUNT,) + f.shape, f.dtype)
            for f in base
        )
    )
    return (
        functools.partial(
            plan_tenants_batched,
            make_tenant_mesh(),
            rounds=8,
            carry_chunks=4,
            carry_layout=NARROW_LAYOUT,
        ),
        (stacked,),
    )


def _tenant_schedule_build(s):
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_tenant_mesh

    base = packed_struct(s)
    stacked = PackedCluster(
        *(
            jax.ShapeDtypeStruct((TENANT_PROBE_COUNT,) + f.shape, f.dtype)
            for f in base
        )
    )
    return (
        functools.partial(
            plan_tenants_scheduled, make_tenant_mesh(), horizon=8, rounds=8
        ),
        (stacked,),
    )


def _tenant_delta_build(s):
    from k8s_spot_rescheduler_tpu.hot_programs import delta_struct

    base = packed_struct(s)
    stacked = tuple(
        jax.ShapeDtypeStruct((TENANT_PROBE_COUNT,) + f.shape, f.dtype)
        for f in base
    )
    d = delta_struct(s)
    deltas = type(d)(
        *(
            jax.ShapeDtypeStruct((TENANT_PROBE_COUNT,) + f.shape, f.dtype)
            for f in d
        )
    )
    return (apply_tenant_deltas, stacked + (deltas,))


HOT_PROGRAMS = {
    "service.tenant_batch": HotProgram(
        build=_tenant_batch_build,
        covers=(
            "parallel.tenant_batch:plan_tenants_batched",
            "parallel.tenant_batch:plan_tenants_batched.local",
        ),
    ),
    "service.tenant_batch_carry": HotProgram(
        build=_tenant_batch_carry_build,
        covers=(
            "parallel.tenant_batch:plan_tenants_batched",
            "parallel.tenant_batch:plan_tenants_batched.local",
        ),
    ),
    "service.tenant_schedule": HotProgram(
        build=_tenant_schedule_build,
        covers=(
            "parallel.tenant_batch:plan_tenants_scheduled",
            "parallel.tenant_batch:plan_tenants_scheduled.local",
        ),
    ),
    "service.tenant_delta_scatter": HotProgram(
        build=_tenant_delta_build,
        covers=("parallel.tenant_batch:apply_tenant_deltas",),
        donate_argnums=tuple(range(11)),
    ),
}
