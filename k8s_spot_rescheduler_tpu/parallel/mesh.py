"""Device-mesh construction.

The solver's two parallel axes (SURVEY.md §2.3) map onto a 2-D
``jax.sharding.Mesh``:

- ``"cand"`` — candidate on-demand nodes (pure data parallelism: the
  fork-per-candidate lanes never communicate);
- ``"spot"`` — the spot-node pool (model-parallel-like: the first-fit
  probe requires a global argmin over spot shards each scan step, an
  ICI collective).

The reference has no analog — its planning loop is strictly sequential on
one CPU (reference rescheduler.go:228-287); this is the scale axis that
replaces it (SURVEY.md §5.7: cluster size is this framework's
"long context").
"""

from __future__ import annotations

from typing import Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

CAND_AXIS = "cand"
SPOT_AXIS = "spot"
TENANT_AXIS = "tenant"


def pick_mesh_shape(n_devices: int) -> Tuple[int, int]:
    """(cand, spot) mesh shape for n devices.

    Candidate lanes are embarrassingly parallel (no collectives), so the
    cand axis gets the larger factor; the spot axis (one pmin per scan
    step) stays small to keep collective latency off the critical path.
    """
    spot = 1
    for s in (2,):
        if n_devices % s == 0 and n_devices > s:
            spot = s
    return n_devices // spot, spot


def make_cand_mesh(devices=None) -> Mesh:
    """A 1-D all-device mesh over the candidate axis only — the
    cand-only sharding layout (parallel/sharded_ffd.py
    ``plan_union_cand_sharded``): every device holds a block of
    candidate lanes with the FULL spot axis replicated, so the complete
    single-chip union program (repair included) runs per block with no
    collectives at all."""
    devices = devices if devices is not None else jax.devices()
    grid = mesh_utils.create_device_mesh(
        (len(devices),), devices=np.asarray(devices)
    )
    return Mesh(grid, (CAND_AXIS,))


def make_tenant_mesh(devices=None) -> Mesh:
    """A 1-D all-device mesh over the TENANT axis — the multi-tenant
    planner service's batching layout (parallel/tenant_batch.py): every
    device holds a block of whole tenant problems, each solved by the
    complete single-chip union program. Tenants are clusters; clusters
    never interact — zero collectives, like the cand-only layout one
    level up the nesting."""
    devices = devices if devices is not None else jax.devices()
    grid = mesh_utils.create_device_mesh(
        (len(devices),), devices=np.asarray(devices)
    )
    return Mesh(grid, (TENANT_AXIS,))


def make_mesh(shape: Tuple[int, int] | None = None, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = pick_mesh_shape(len(devices))
    n = shape[0] * shape[1]
    if n > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices, have {len(devices)}"
        )
    grid = mesh_utils.create_device_mesh(shape, devices=np.asarray(devices[:n]))
    return Mesh(grid, (CAND_AXIS, SPOT_AXIS))
