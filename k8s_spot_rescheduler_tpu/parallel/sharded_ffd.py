"""Mesh-sharded batched first-fit solver (shard_map + ICI collectives).

Semantics are identical to solver/ffd.py (itself bit-identical to the
serial reference nest, rescheduler.go:334-370); the difference is layout:

- candidate lanes are sharded over the ``cand`` mesh axis — no
  communication at all (the Fork/Revert lanes are independent);
- the spot pool is sharded over the ``spot`` mesh axis. First-fit needs
  the *globally first* fitting spot node each scan step, so each device
  computes its local first-fit index, converts it to a global index, and a
  ``lax.pmin`` over the spot axis elects the winner — one small [C_local]
  collective per scan step riding ICI. The winning device (and only it)
  applies the capacity/count/affinity update to its local shard.

This is the "blockwise/ring processing of the (pods × nodes) fit matrix"
the survey calls for (SURVEY.md §5.7): the 50k-pod × 5k-node problem never
materializes on one chip.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6: top-level export, replication check named check_vma
    from jax import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
except ImportError:  # older jax: experimental module, kwarg check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.parallel.mesh import CAND_AXIS, SPOT_AXIS, make_mesh
from k8s_spot_rescheduler_tpu.predicates.masks import fit_mask_t
from k8s_spot_rescheduler_tpu.solver.carry import WIDE_LAYOUT
from k8s_spot_rescheduler_tpu.solver.ffd import (
    _spot_statics as _ffd_spot_statics,
    _widen as _ffd_widen,
)
from k8s_spot_rescheduler_tpu.solver.result import SolveResult

_BIG = jnp.int32(2**30)


def _local_step(static, best_fit, carry, slot):
    """One pod-slot placement on this device's (cand, spot) block.
    The carry is the DELTA-form narrow state (solver/carry.CarryLayout)
    widened on read against the replicated block statics — the same
    one-site discipline as solver/ffd."""
    spot_static, s_local, s_offset = static
    used, dcount, daff, feasible = carry
    req, valid, tol, aff = slot  # local [Cl,R], [Cl], [Cl,W], [Cl,A]
    free, count, aff_acc = _ffd_widen(spot_static, used, dcount, daff)

    fits = fit_mask_t(
        jnp,
        free_t=free,  # [Cl, R, Sl] — spot axis minor (see fit_mask_t)
        count=count,
        max_pods=spot_static.max_pods,
        node_taints_t=spot_static.taints_t,  # [W, Sl]
        node_ok=spot_static.ok,
        node_aff_t=aff_acc,  # [Cl, A, Sl]
        req=req,
        tol=tol,
        aff=aff,
    )  # [Cl, Sl]

    local_any = jnp.any(fits, axis=-1)
    if best_fit:
        # two collectives: elect the global minimum slack, then the first
        # node achieving it (slack is integral in f32, equality is exact)
        slack = jnp.where(fits, free[:, 0, :] - req[:, None, 0], jnp.inf)
        local_min = jnp.min(slack, axis=-1)
        global_min = jax.lax.pmin(local_min, SPOT_AXIS)  # [Cl]
        at_min = fits & (slack == global_min[:, None])
        local_first = jnp.argmax(at_min, axis=-1).astype(jnp.int32)
        my_global = jnp.where(
            jnp.any(at_min, axis=-1), s_offset + local_first, _BIG
        )
    else:
        local_first = jnp.argmax(fits, axis=-1).astype(jnp.int32)
        my_global = jnp.where(local_any, s_offset + local_first, _BIG)
    # elect the globally-first fitting spot node across spot shards
    winner = jax.lax.pmin(my_global, SPOT_AXIS)  # [Cl]
    any_fit = winner < _BIG
    place = valid & any_fit

    local_winner = winner - s_offset
    in_shard = place & (local_winner >= 0) & (local_winner < s_local)
    onehot = (jnp.arange(fits.shape[-1])[None, :] == local_winner[:, None]) & (
        in_shard[:, None]
    )

    used = used + (onehot[:, None, :] * req[:, :, None]).astype(used.dtype)
    dcount = dcount + onehot.astype(dcount.dtype)
    daff = daff | jnp.where(
        onehot[:, None, :], aff[:, :, None], 0
    ).astype(daff.dtype)
    feasible = feasible & (any_fit | ~valid)

    chosen = jnp.where(place, winner, jnp.int32(-1))
    return (used, dcount, daff, feasible), chosen


def _sharded_plan_local(best_fit, layout, packed: PackedCluster):
    """Runs on every device over its local block (inside shard_map)."""
    Cl = packed.slot_req.shape[0]
    Sl = packed.spot_free.shape[0]
    R = packed.slot_req.shape[2]
    A = packed.spot_aff.shape[1]
    s_offset = jax.lax.axis_index(SPOT_AXIS).astype(jnp.int32) * Sl

    spot_static = _ffd_spot_statics(packed)
    carry = (
        jnp.zeros((Cl, R, Sl), layout.used),
        jnp.zeros((Cl, Sl), layout.count),
        jnp.zeros((Cl, A, Sl), layout.aff),
        jnp.asarray(packed.cand_valid),
    )
    static = (spot_static, jnp.int32(Sl), s_offset)
    slots = (
        jnp.moveaxis(packed.slot_req, 1, 0),
        jnp.moveaxis(packed.slot_valid, 1, 0),
        jnp.moveaxis(packed.slot_tol, 1, 0),
        jnp.moveaxis(packed.slot_aff, 1, 0),
    )
    (u, dc, da, feasible), chosen = jax.lax.scan(
        functools.partial(_local_step, static, best_fit), carry, slots
    )
    feasible = feasible & jnp.asarray(packed.cand_valid)
    assignment = jnp.where(feasible[None, :], chosen, -1).T  # [Cl, K]
    return feasible, assignment


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _pad_axes(packed: PackedCluster, Cp: int, Sp: int) -> PackedCluster:
    """Pad the candidate/spot axes to the given sizes with inert entries
    (invalid lanes, never-fitting nodes). Padding spot nodes sit at the
    *end* of the probe order so first-fit semantics are unchanged."""
    C = packed.slot_req.shape[0]
    S = packed.spot_free.shape[0]
    if Cp == C and Sp == S:
        return packed

    def pad(arr, n, axis=0):
        if n == arr.shape[axis]:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, n - arr.shape[axis])
        return jnp.pad(arr, widths)

    return PackedCluster(
        slot_req=pad(packed.slot_req, Cp),
        slot_valid=pad(packed.slot_valid, Cp),
        slot_tol=pad(packed.slot_tol, Cp),
        slot_aff=pad(packed.slot_aff, Cp),
        cand_valid=pad(packed.cand_valid, Cp),
        spot_free=pad(packed.spot_free, Sp),
        spot_count=pad(packed.spot_count, Sp),
        spot_max_pods=pad(packed.spot_max_pods, Sp),
        spot_taints=pad(packed.spot_taints, Sp),
        spot_ok=pad(packed.spot_ok, Sp),  # padded nodes: spot_ok=False
        spot_aff=pad(packed.spot_aff, Sp),
    )


def _pad_to_mesh(packed: PackedCluster, mesh: Mesh) -> PackedCluster:
    C = packed.slot_req.shape[0]
    S = packed.spot_free.shape[0]
    return _pad_axes(
        packed,
        _round_up(C, mesh.shape[CAND_AXIS]),
        _round_up(S, mesh.shape[SPOT_AXIS]),
    )


def plan_ffd_sharded(
    mesh: Mesh,
    packed: PackedCluster,
    best_fit: bool = False,
    layout=WIDE_LAYOUT,
) -> SolveResult:
    """Shard the PackedCluster over the mesh and solve. Axes that don't
    divide the mesh are padded with inert entries and sliced back out.
    ``layout`` narrows each device's delta carries (solver/carry.py) —
    the caller passes only what ``carry_layout(packed)`` proves."""
    C = packed.slot_req.shape[0]
    packed = _pad_to_mesh(packed, mesh)
    cand_sharded = PackedCluster(
        slot_req=P(CAND_AXIS),
        slot_valid=P(CAND_AXIS),
        slot_tol=P(CAND_AXIS),
        slot_aff=P(CAND_AXIS),
        cand_valid=P(CAND_AXIS),
        spot_free=P(SPOT_AXIS),
        spot_count=P(SPOT_AXIS),
        spot_max_pods=P(SPOT_AXIS),
        spot_taints=P(SPOT_AXIS),
        spot_ok=P(SPOT_AXIS),
        spot_aff=P(SPOT_AXIS),
    )
    fn = shard_map(
        functools.partial(_sharded_plan_local, best_fit, layout),
        mesh=mesh,
        in_specs=(cand_sharded,),
        out_specs=(P(CAND_AXIS), P(CAND_AXIS, None)),
        check_vma=False,
    )
    feasible, assignment = fn(packed)
    return SolveResult(feasible=feasible[:C], assignment=assignment[:C])


def plan_union_cand_sharded(
    mesh: Mesh,
    packed: PackedCluster,
    *,
    rounds: int = 0,
    best_fit_fallback: bool = True,
    repair_spot_chunks: int = 1,
    carry_chunks: int = 0,
    carry_layout=None,
    use_pallas: bool = False,
) -> SolveResult:
    """Candidate-ONLY sharding: each device holds a block of candidate
    lanes with the FULL spot axis replicated, and runs the complete
    single-chip union program — first-fit ∪ best-fit ∪ REPAIR — on its
    block. Candidate lanes are the Fork/Revert forks (reference
    rescheduler.go:269-275): they never interact, so the block program
    needs no collectives, and repair's per-lane eject-reinsert search
    state (solver/repair.py) exists unchanged — the quality phase the
    2-D cand×spot layout must drop survives past single-chip scale
    whenever one lane's full spot state still fits one device
    (solver/memory.estimate_union_hbm_bytes at C/n). Past THAT,
    ``repair_spot_chunks`` > 1 runs the elect-then-commit spot-chunked
    repair inside each device (solver/repair.plan_repair_chunked,
    bit-identical), shrinking the per-round working set to
    O(S / chunks) and carrying repair further still — only when even
    the fully-chunked block exceeds the budget does the dispatch fall
    to the repair-less 2-D layout. ``carry_chunks`` >= 1 swaps the block
    program for the CARRY-STREAMED union
    (solver/fallback.with_repair_streamed, ROADMAP 5): narrow delta
    carries under ``carry_layout`` (solver/carry.carry_layout of the
    pack; NARROW_LAYOUT when None) with the spot axis streamed — repair
    stays live past even the fully-chunked wide ceiling, bit-identical
    results throughout. ``use_pallas`` swaps the streamed union's
    best-fit pass for the fused Pallas stream kernel (bit-identical;
    ops/pallas_ffd.plan_stream_bf_pallas). ``mesh`` is the 1-D
    all-device mesh of ``parallel/mesh.make_cand_mesh``."""
    from k8s_spot_rescheduler_tpu.solver.fallback import union_program

    solve = union_program(
        rounds,
        best_fit_fallback,
        repair_spot_chunks=repair_spot_chunks,
        carry_chunks=carry_chunks,
        carry_layout=carry_layout,
        use_pallas=use_pallas,
    )
    C = packed.slot_req.shape[0]
    packed = _pad_axes(
        packed,
        _round_up(C, mesh.shape[CAND_AXIS]),
        packed.spot_free.shape[0],
    )
    cand_only = PackedCluster(
        slot_req=P(CAND_AXIS),
        slot_valid=P(CAND_AXIS),
        slot_tol=P(CAND_AXIS),
        slot_aff=P(CAND_AXIS),
        cand_valid=P(CAND_AXIS),
        spot_free=P(),  # replicated: each lane block sees the whole pool
        spot_count=P(),
        spot_max_pods=P(),
        spot_taints=P(),
        spot_ok=P(),
        spot_aff=P(),
    )

    def local(p):
        res = solve(p)
        return res.feasible, res.assignment

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(cand_only,),
        out_specs=(P(CAND_AXIS), P(CAND_AXIS, None)),
        check_vma=False,
    )
    feasible, assignment = fn(packed)
    return SolveResult(feasible=feasible[:C], assignment=assignment[:C])


def make_sharded_planner(mesh_shape: Tuple[int, int] | None = None):
    """A jitted solver callable bound to a mesh built from the visible
    devices (the SolverPlanner 'sharded' backend)."""
    mesh = make_mesh(mesh_shape)
    return jax.jit(functools.partial(plan_ffd_sharded, mesh))


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): both mesh layouts, traced over meshes built
# from the visible devices (the audit runs on >=8 virtual CPU devices;
# tracing is shape-only, so the mesh is just a layout declaration).
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)


def _sharded_2d_build(s):
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh

    return (
        functools.partial(plan_ffd_sharded, make_mesh(None)),
        (packed_struct(s),),
    )


def _cand_sharded_build(s):
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_cand_mesh

    return (
        functools.partial(
            plan_union_cand_sharded,
            make_cand_mesh(),
            rounds=8,
            repair_spot_chunks=4,
        ),
        (packed_struct(s),),
    )


def _cand_carry_build(s):
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_cand_mesh
    from k8s_spot_rescheduler_tpu.solver.carry import NARROW_LAYOUT

    return (
        functools.partial(
            plan_union_cand_sharded,
            make_cand_mesh(),
            rounds=8,
            carry_chunks=4,
            carry_layout=NARROW_LAYOUT,
        ),
        (packed_struct(s),),
    )


HOT_PROGRAMS = {
    "sharded.ffd_2d": HotProgram(
        build=_sharded_2d_build,
        covers=(
            "parallel.sharded_ffd:_sharded_plan_local",
            "parallel.sharded_ffd:plan_ffd_sharded",
        ),
    ),
    "sharded.union_cand": HotProgram(
        build=_cand_sharded_build,
        covers=("parallel.sharded_ffd:plan_union_cand_sharded.local",),
    ),
    "sharded.union_cand_carry": HotProgram(
        build=_cand_carry_build,
        covers=("parallel.sharded_ffd:plan_union_cand_sharded.local",),
    ),
}
