"""Device-mesh parallelism for the drain solver."""

from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh, pick_mesh_shape
from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
    make_sharded_planner,
    plan_ffd_sharded,
)

__all__ = [
    "make_mesh",
    "pick_mesh_shape",
    "make_sharded_planner",
    "plan_ffd_sharded",
]
