"""Node-drain actuation state machine.

Host-side reimplementation of the reference's ``scaler`` package
(reference scaler/scaler.go:41-146):

1. taint the node ToBeDeleted so the scheduler won't re-place evicted pods
   onto it mid-drain (scaler.go:77 ``MarkToBeDeleted``);
2. evict every pod, retrying each failed eviction every
   ``eviction_retry_time`` until ``pod_eviction_timeout`` expires
   (scaler.go:47-62). The reference fans out one goroutine per pod and
   fans in over a channel (scaler.go:93-113); here each retry round
   fans the not-yet-evicted set out over a bounded thread pool — one
   slow apiserver call costs one pod-latency per round, not one per
   pod — and emits the reference's per-pod Normal event before the
   first attempt (scaler.go:44);
3. poll every 5 s until every pod is confirmed off the node or the
   timeout passes (scaler.go:119-144);
4. on success un-taint — the drained node stays schedulable as spare
   capacity for the next drain (scaler.go:138-141, README.md:117);
   on any failure un-taint and emit a warning event (the reference's
   deferred cleanup, scaler.go:83-88).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

from k8s_spot_rescheduler_tpu.io.cluster import ClusterClient, EventSink
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeSpec,
    PodSpec,
    Taint,
    TO_BE_DELETED_TAINT,
    rescheduler_taint_value,
)
from k8s_spot_rescheduler_tpu.utils.clock import Clock
from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils import tracing

VERIFY_POLL_INTERVAL = 5.0  # scaler.go:143 time.Sleep(5 * time.Second)

# The reference spawns one goroutine per pod (scaler.go:93-98); Python
# threads are heavier, so the fan-out is bounded. Workers only call the
# (thread-safe) eviction endpoint and bump a (thread-safe) counter —
# events and retry bookkeeping stay on the actuator thread.
EVICTION_POOL_SIZE = 32


def _evict_round(
    client: ClusterClient,
    pods: Sequence[PodSpec],
    max_graceful_termination: int,
) -> Tuple[List[PodSpec], Optional[Exception]]:
    """One parallel eviction pass; returns (failed pods, last error)."""

    def attempt(pod: PodSpec) -> Optional[Exception]:
        try:
            client.evict_pod(pod, max_graceful_termination)
            metrics.update_evictions_count()
            return None
        except Exception as err:  # noqa: BLE001 — retried until deadline
            return err

    if len(pods) == 1:  # no pool for the common one-pod round
        errs = [attempt(pods[0])]
    else:
        with ThreadPoolExecutor(
            max_workers=min(len(pods), EVICTION_POOL_SIZE)
        ) as pool:
            errs = list(pool.map(attempt, pods))
    failed = [pod for pod, err in zip(pods, errs) if err is not None]
    last_error = next(
        (err for err in reversed(errs) if err is not None), None
    )
    return failed, last_error


class DrainError(Exception):
    pass


def drain_node(
    client: ClusterClient,
    recorder: EventSink,
    node: NodeSpec,
    pods: Sequence[PodSpec],
    *,
    clock: Clock,
    max_graceful_termination: int,
    pod_eviction_timeout: float,
    eviction_retry_time: float,
    identity: str = "",
    schedule_step: int = -1,
) -> None:
    """Drain ``node`` of ``pods``; raises DrainError on failure
    (reference scaler.go:68-146 ``DrainNode``).

    ``schedule_step`` >= 0 marks a drain executed from a device-cut
    drain schedule (planner/schedule.py): the step index rides the
    node's Normal event and the eviction trace spans, so a postmortem
    can tell schedule-executed drains from per-tick plans. The cadence
    is unchanged either way — the schedule changes how drains are
    DECIDED (one fetch per horizon), never how they are verified.

    The taint is stamped with an ownership value (``identity`` — the
    replica's stable holder id — plus a wall timestamp): the cluster
    autoscaler applies the SAME taint key during its own scale-downs, so
    the controller's orphaned-taint sweep only ever removes taints
    carrying this marker (models/cluster.py ``rescheduler_taint_value``).
    """
    # clock.wall() on purpose (no monotonic fallback): the stamp is
    # compared across processes/replicas, and silently writing
    # seconds-since-boot would make another sweeper misjudge the
    # taint's age — a non-conforming Clock must fail loudly here
    taint = Taint(
        TO_BE_DELETED_TAINT,
        rescheduler_taint_value(identity, clock.wall()),
        "NoSchedule",
    )
    try:
        client.add_taint(node.name, taint)
    except Exception as err:  # noqa: BLE001 — any apiserver failure aborts
        recorder.event(
            "Node", node.name, "Warning", "ReschedulerFailed",
            f"failed to mark the node as draining/unschedulable: {err}",
        )
        raise DrainError(str(err)) from err
    recorder.event(
        "Node", node.name, "Normal", "Rescheduler",
        "marked the node as draining/unschedulable"
        + (
            f" (drain schedule step {schedule_step})"
            if schedule_step >= 0
            else ""
        ),
    )

    drain_successful = False
    try:
        retry_until = clock.now() + pod_eviction_timeout

        # Per-pod announcement before the first attempt (scaler.go:44).
        for pod in pods:
            recorder.event(
                "Pod", pod.uid, "Normal", "Rescheduler",
                "deleting pod from on-demand node",
            )

        # Eviction fan-out with the reference's retry cadence: every pod is
        # attempted in parallel (bounded pool standing in for scaler.go's
        # goroutine-per-pod, 93-113), then the failed set is retried each
        # retry period until the deadline (scaler.go:47-62).
        remaining: List[PodSpec] = list(pods)
        while remaining:
            with tracing.span(
                "drain.evict", pods=len(remaining),
                **({"schedule_step": schedule_step}
                   if schedule_step >= 0 else {}),
            ):
                remaining, err = _evict_round(
                    client, remaining, max_graceful_termination
                )
            if err is not None:
                last_error = err
            if remaining:
                if clock.now() + eviction_retry_time >= retry_until:
                    for pod in remaining:
                        recorder.event(
                            "Pod", pod.uid, "Warning", "ReschedulerFailed",
                            "failed to delete pod from on-demand node",
                        )
                    raise DrainError(
                        f"failed to drain node {node.name}, due to following "
                        f"errors: {last_error}"
                    )
                clock.sleep(eviction_retry_time)

        # Verification poll (scaler.go:119-144): all pods must be off the
        # node before the deadline. A pod observed gone is memoized (it
        # was evicted), so each round re-checks only the rest — and a
        # flaky GET marks only ITS pod as not-confirmed while the
        # remaining pods are still checked this round, instead of one
        # transient error burning the whole 5 s poll interval for all.
        # Success requires every gone verdict on the FINAL round: verdicts
        # memoized in earlier rounds get one fresh confirming read, so a
        # single anomalous observation (e.g. a stale-serving client
        # layer) cannot declare a still-running pod evicted and the node
        # drained. The common case — everything gone in one round — pays
        # no extra reads.
        gone: set = set()
        while clock.now() < retry_until + VERIFY_POLL_INTERVAL:
            fresh: set = set()  # gone verdicts observed THIS round
            with tracing.span(
                "drain.verify", remaining=len(pods) - len(gone)
            ):
                for pod in pods:
                    if pod.uid in gone:
                        continue
                    try:
                        returned = client.get_pod(pod.namespace, pod.name)
                    except Exception as err:  # noqa: BLE001 — scaler.go:129-133
                        log.error("Failed to check pod %s: %s", pod.uid, err)
                        continue  # only this pod counts as not-yet-gone
                    if returned is None or returned.node_name != node.name:
                        fresh.add(pod.uid)
                    else:
                        # expected while evictions propagate — the
                        # reference logs it at plain glog info
                        # (scaler/scaler.go:131-135), not error;
                        # vlog-gated here so proof artifacts and quiet
                        # production logs don't carry per-poll noise
                        log.vlog(2, "Not deleted yet %s", pod.name)
            confirmed = len(gone) + len(fresh) == len(pods)
            if confirmed:
                # re-confirm earlier rounds' memoized verdicts with one
                # fresh read each; a pod found back demotes to not-gone
                # and the poll continues
                for pod in pods:
                    if pod.uid in fresh or pod.uid not in gone:
                        continue
                    try:
                        returned = client.get_pod(pod.namespace, pod.name)
                    except Exception as err:  # noqa: BLE001
                        log.error(
                            "Failed to re-confirm pod %s: %s", pod.uid, err
                        )
                        gone.discard(pod.uid)
                        confirmed = False
                        continue
                    if returned is not None and returned.node_name == node.name:
                        log.error(
                            "Pod %s reappeared on %s after being observed "
                            "gone; resuming verification", pod.name, node.name,
                        )
                        gone.discard(pod.uid)
                        confirmed = False
            gone |= fresh
            if confirmed:
                log.vlog(4, "All pods removed from %s", node.name)
                drain_successful = True
                recorder.event(
                    "Node", node.name, "Normal", "Rescheduler",
                    "marked the node as drained/schedulable",
                )
                try:
                    client.remove_taint(node.name, TO_BE_DELETED_TAINT)
                except Exception as err:  # noqa: BLE001
                    log.error("Failed to clean taint on %s: %s", node.name, err)
                return
            clock.sleep(VERIFY_POLL_INTERVAL)
        raise DrainError(
            f"failed to drain node {node.name}: pods remaining after timeout"
        )
    finally:
        if not drain_successful:
            # deferred cleanup (scaler.go:83-88); cleanup failures must not
            # mask the original DrainError or crash the loop
            try:
                client.remove_taint(node.name, TO_BE_DELETED_TAINT)
            except Exception as err:  # noqa: BLE001
                log.error("Failed to clean taint on %s: %s", node.name, err)
            recorder.event(
                "Node", node.name, "Warning", "ReschedulerFailed",
                "failed to drain the node, aborting drain.",
            )
