"""Actuation layer: node drain state machine."""

from k8s_spot_rescheduler_tpu.actuator.drain import DrainError, drain_node

__all__ = ["DrainError", "drain_node"]
