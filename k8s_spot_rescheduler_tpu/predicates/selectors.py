"""Canonical label-selector requirements — the widened selector algebra.

Round 5 widens every pod-affinity/spread selector from the matchLabels
dict shape to the full k8s ``LabelSelector`` operator surface
(In / NotIn / Exists / DoesNotExist, multi-value In) plus explicit
cross-namespace ``namespaces`` lists and any number of required terms
per topology family. The reference gets all of these free through the
real scheduler's InterPodAffinity / PodTopologySpread predicates
(reference rescheduler.go:344; predicate list README.md:103-114); here
they become data every decode path (io/kube.py, io/watch.py via
decode_pod, native/ingest.cc via io/native_ingest.py) must canonicalize
*identically*, so the packers intern equal constraints to equal bits.

Canonical forms (plain tuples — hashable, orderable, blob-free):

- **requirement** ``(key, op, values)`` with ``op`` one of
  In/NotIn/Exists/DoesNotExist and ``values`` a sorted, deduplicated
  tuple (empty for Exists/DoesNotExist — k8s validation rejects values
  there, and decode treats violations as unmodeled);
- **selector** — sorted tuple of requirements; matchLabels pairs enter
  as single-value In requirements. Two semantically equal selectors
  written differently may intern to two bits — harmless, both verdicts
  are computed correctly; equality is only an interning optimization;
- **term** ``(namespaces, selector)`` with ``namespaces`` a sorted
  non-empty tuple of namespace names. An absent/empty ``namespaces``
  field resolves to the pod's own namespace at decode time, so the
  implicit form and an explicit own-namespace list are one identity.

Matching semantics follow k8s.io/apimachinery ``labels.Requirement``:
NotIn and DoesNotExist match when the key is absent.
"""

from __future__ import annotations

from typing import Dict, Tuple

# Operator vocabulary for pod-label selectors (LabelSelectorOperator).
# Node-affinity expressions additionally use Gt/Lt/FieldIn/FieldNotIn —
# those stay in predicates/masks.match_expr and never appear here.
SELECTOR_OPS = ("In", "NotIn", "Exists", "DoesNotExist")

Req = Tuple[str, str, Tuple[str, ...]]
Selector = Tuple[Req, ...]
Term = Tuple[Tuple[str, ...], Selector]


def canon_labels(match: Dict[str, str]) -> Selector:
    """matchLabels dict -> canonical selector (each pair a single-value
    In requirement)."""
    return tuple(sorted((k, "In", (v,)) for k, v in match.items()))


def canon_selector(reqs) -> Selector:
    """Sort + dedupe a requirement iterable into canonical form; value
    lists are assumed already sorted/deduped by the decoder."""
    return tuple(sorted(set(reqs)))


def req_matches(req: Req, labels) -> bool:
    """One requirement against a pod's labels (k8s labels.Requirement
    semantics: NotIn/DoesNotExist match when the key is absent)."""
    key, op, values = req
    v = labels.get(key)
    if op == "In":
        return v is not None and v in values
    if op == "NotIn":
        return v is None or v not in values
    if op == "Exists":
        return v is not None
    return v is None  # DoesNotExist


# A canonical selector that can match NO pod (the "" key must both
# exist and not exist): the exact encoding of k8s's nil-selector
# semantics (labels.Nothing()) — decode_pdb uses it for PDBs created
# without a spec.selector, which select zero pods.
MATCH_NOTHING: Selector = (("", "DoesNotExist", ()), ("", "Exists", ()))


def selector_matches(sel: Selector, labels) -> bool:
    """AND over the selector's requirements. The EMPTY selector matches
    everything (k8s: an empty LabelSelector selects all objects) — the
    affinity decoders never produce one (empty selectors stay
    unmodeled), but ``decode_pdb`` deliberately does: a PDB's ``{}``
    selector selects every pod in its namespace, and the empty selector
    is also its conservative fallback for unparseable shapes. A nil
    PDB selector is ``MATCH_NOTHING`` instead."""
    return all(req_matches(r, labels) for r in sel)


# The all-namespaces scope (a term with ``namespaceSelector: {}``,
# which k8s defines as selecting every namespace). Namespace names are
# DNS-1123 labels, so a literal "*" namespace cannot exist — the
# sentinel is collision-free.
ALL_NAMESPACES = ("*",)


def term_matches(term: Term, pod_namespace: str, labels) -> bool:
    """Does a pod (namespace + labels) fall in the term's scope and
    match its selector? This is both the presence direction (which pods
    set a universe term's bit) and the node-side resident check."""
    namespaces, sel = term
    return (
        namespaces == ALL_NAMESPACES or pod_namespace in namespaces
    ) and selector_matches(sel, labels)


def selector_matches_nothing(sel: Selector) -> bool:
    """True iff NO label assignment can satisfy the selector — exact,
    by per-key analysis (keys are independent):

    - DoesNotExist together with In/Exists on one key is impossible;
    - the intersection of a key's In sets minus its NotIn values being
      empty is impossible;
    - NotIn/Exists alone are always satisfiable (the value domain is
      unbounded from the selector's point of view).

    Anti-affinity terms whose selector matches nothing constrain
    nothing and are dropped exactly; positive-affinity terms keep the
    term (no resident can ever match -> every node repels the carrier,
    which is the scheduler's exact verdict)."""
    by_key: Dict[str, list] = {}
    for req in sel:
        by_key.setdefault(req[0], []).append(req)
    for reqs in by_key.values():
        has_dne = any(op == "DoesNotExist" for _, op, _ in reqs)
        needs_value = any(op in ("In", "Exists") for _, op, _ in reqs)
        if has_dne:
            if needs_value:
                return True
            continue  # satisfiable by absence (NotIn matches absent too)
        in_sets = [set(v) for _, op, v in reqs if op == "In"]
        if in_sets:
            not_in = set()
            for _, op, v in reqs:
                if op == "NotIn":
                    not_in.update(v)
            if not (set.intersection(*in_sets) - not_in):
                return True
        # NotIn/Exists only: always satisfiable
    return False


def term_key(term: Term) -> str:
    """Deterministic hash key for a term (predicates/masks.affinity_bits
    group hashing). Decode guarantees namespaces, keys, operators and
    values are free of the \\x1c-\\x1f separator bytes, so the encoding
    is collision-free across distinct canonical terms."""
    namespaces, sel = term
    return "\x1c".join(namespaces) + "\x1d" + "\x1e".join(
        f"{k}\x1f{op}\x1f" + "\x1c".join(vals) for k, op, vals in sel
    )


def canon_match_terms(value, own_namespace: str) -> Tuple[Term, ...]:
    """Normalize a PodSpec affinity field to canonical terms.

    Accepts the legacy matchLabels dict shorthand (own-namespace, one
    term — what synthetic generators and tests construct), an already-
    canonical tuple of terms, or ()/None. The shorthand keeps every
    existing call site valid while the decode paths emit full terms."""
    if not value:
        return ()
    if isinstance(value, dict):
        return (((own_namespace,), canon_labels(value)),)
    return tuple(sorted(set(value)))


def canon_spread_entries(value) -> Tuple:
    """Normalize spread_constraints entries: legacy (topo, skew,
    ((key, value), ...)) items become (topo, skew, selector) with
    single-value In requirements; canonical entries pass through."""
    if not value:
        return ()
    out = []
    for topo, skew, items in value:
        reqs = tuple(
            sorted(
                item if len(item) == 3 else (item[0], "In", (item[1],))
                for item in items
            )
        )
        out.append((topo, int(skew), reqs))
    return tuple(sorted(set(out)))
