"""Vectorized scheduler-predicate oracle."""

from k8s_spot_rescheduler_tpu.predicates.masks import (
    AFFINITY_WORDS,
    TaintTable,
    affinity_bits,
    fit_mask,
    intern_taints,
    pod_affinity_mask,
    pod_toleration_mask,
)

__all__ = [
    "AFFINITY_WORDS",
    "TaintTable",
    "affinity_bits",
    "fit_mask",
    "intern_taints",
    "pod_affinity_mask",
    "pod_toleration_mask",
]
