"""Vectorized scheduler predicates.

The reference asks the real kube-scheduler "would pod p fit on node n?" one
(pod, node) pair at a time through ``PredicateChecker.CheckPredicates``
(reference rescheduler.go:344; predicate list README.md:103-114: resource
fit, taints/tolerations, node readiness, affinity, ...). Here the same
questions are answered for *all* pairs at once from dense arrays:

- **resource fit** — elementwise ``free >= request`` over the resource axis
  plus a pod-count-vs-max-pods check;
- **taints/tolerations** — taints on spot nodes are interned into a global
  bit table; a node's taint bitmask AND NOT the pod's toleration bitmask
  must be zero. Only hard effects (NoSchedule/NoExecute) block placement;
  PreferNoSchedule is advisory and excluded from the table;
- **readiness/schedulability** — folded into a per-node validity bit
  (the reference only ever sees ready nodes via ``NewReadyNodeLister``,
  rescheduler.go:154, and the scheduler rejects cordoned nodes);
- **anti-affinity** — simplified hostname-topology groups, hashed onto a
  fixed 64-bit mask. Hash collisions can only *forbid* extra placements,
  never allow an invalid one — conservative in the safe direction (a plan
  we approve must never strand a pod; SURVEY.md §7 "hard parts" (e)).

All mask math is uint32 words so it runs identically under NumPy (oracle
solver) and jnp (TPU solver).
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from typing import List, Sequence, Tuple

import numpy as np

from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeSpec,
    PodSpec,
    Taint,
    TO_BE_DELETED_TAINT,
)
from k8s_spot_rescheduler_tpu.predicates.selectors import (
    selector_matches,
    term_key,
    term_matches,
)

HARD_EFFECTS = ("NoSchedule", "NoExecute")

# strconv.ParseInt(s, 10, 64)-compatible integer literal: optional sign
# (Go accepts '+' and '-'), ASCII digits only (\d would admit Unicode
# digits Go rejects), no '_' or whitespace; range-checked to int64 below.
_INT_RE = re.compile(r"[+-]?[0-9]+")
_INT64_MIN, _INT64_MAX = -(2**63), 2**63 - 1


def _parse_int64(s: str):
    """int(s) under Go strconv.ParseInt(s, 10, 64) rules; None on any
    input Go rejects (syntax or 64-bit range)."""
    if not _INT_RE.fullmatch(s):
        return None
    v = int(s)
    if not _INT64_MIN <= v <= _INT64_MAX:
        return None
    return v

# Anti-affinity groups hash onto 64 bits = 2 uint32 words.
AFFINITY_WORDS = 2
AFFINITY_BITS = 32 * AFFINITY_WORDS


@dataclasses.dataclass
class TaintTable:
    """Global interning of hard taints found on spot nodes."""

    taints: List[Taint]
    words: int  # number of uint32 words per mask

    def index(self, taint: Taint) -> int:
        return self.taints.index(taint)


def intern_taints(nodes: Sequence[NodeSpec]) -> TaintTable:
    """Collect distinct hard taints across ``nodes`` into a bit table.

    The actuator's drain taint (TO_BE_DELETED_TAINT, reference
    scaler/scaler.go:77) is always interned so a draining node never
    receives planned pods.
    """
    seen: dict = {}
    for node in nodes:
        for taint in node.taints:
            if taint.effect in HARD_EFFECTS and taint not in seen:
                seen[taint] = len(seen)
    drain = Taint(TO_BE_DELETED_TAINT, "", "NoSchedule")
    if drain not in seen:
        seen[drain] = len(seen)
    taints = list(seen)
    words = max(1, -(-len(taints) // 32))
    return TaintTable(taints=taints, words=words)


# --- pseudo-taints: nodeSelector and unmodeled constraints ---------------
#
# The kube-scheduler's NodeSelector/affinity/volume predicates don't fit
# the "node repels pod" shape of taints, but they DO fit the same bit
# algebra inverted: define a pseudo-taint per distinct nodeSelector
# (key, value) pair, set on every node that LACKS the label; a pod that
# requires the pair simply doesn't tolerate it. Constraints the framework
# can't express (required node-affinity expressions, PVC topology) become
# one "unplaceable" pseudo-taint set on every node that only the affected
# pod fails to tolerate. The payoff: full NodeSelector semantics and
# safe-direction conservatism for the rest, with ZERO changes to any
# solver or the Pallas kernel — they already AND these words.


@dataclasses.dataclass(frozen=True)
class SelectorBit:
    """Pseudo-taint for one required node label (key=value)."""

    key: str
    value: str


@dataclasses.dataclass(frozen=True)
class NodeAffinityBit:
    """Pseudo-taint for one distinct required node-affinity expression
    set (canonical terms: OR of ANDs of (key, op, values)). Set on every
    node that does NOT satisfy the requirement; only pods carrying
    exactly this requirement fail to tolerate it.

    This generalizes the SelectorBit trick: ANY pure node-property
    predicate collapses to one interned bit whose node side is evaluated
    on host at pack time — the solvers' bit algebra never changes.
    Replaces the reference's reliance on the real scheduler's
    node-affinity predicate (reference rescheduler.go:344; predicate
    list README.md:103-114)."""

    terms: Tuple  # ((key, op, (values...)), ...) per term, OR of terms


@dataclasses.dataclass(frozen=True)
class PodAffinityBit:
    """Pseudo-taint for one distinct required POSITIVE pod-affinity
    TERM (round-5 canonical shape, predicates/selectors.py: a
    namespaces scope + a full-operator selector; hostname topology).
    Set on every spot node that does NOT currently host a pod in the
    term's scope matched by its selector; only pods carrying this term
    fail to tolerate it — the inverted-taint encoding of "may only join
    a node with a match". A pod with several positive terms simply
    fails to tolerate several bits (every term must hold).

    Unlike every other pseudo-taint, the node side depends on the pods
    RESIDENT on the node this tick, not on node properties — so it is
    evaluated against the packers' per-tick resident view and excluded
    from any label-keyed node-mask caches. Conservative dynamics: the
    plan's own placements could only create additional matches, so
    counting pre-plan residents only can lose a drain but never approve
    a stranding one."""

    namespaces: Tuple  # sorted namespace scope of the term
    items: Tuple  # canonical selector requirements (key, op, values)


@dataclasses.dataclass(frozen=True)
class ZonePodAffinityBit:
    """Pseudo-taint for one required POSITIVE pod-affinity TERM with
    ZONE topology, per CARRIER CONTEXT: the sorted zones hosting a
    qualifying match this tick. Set on every spot node that lacks the
    zone label or whose zone is not in ``allowed_zones``; only the
    carrier fails to tolerate it. A carrier with several zone terms
    carries several context bits (every term must hold).

    Conservative in two deliberate ways: matches are counted from
    pre-plan COUNTED residents only (in-plan placements could only add
    matches — ignoring them loses a drain, never strands), and matches
    residing on the carrier's own candidate node are EXCLUDED from its
    context — they leave in the same drain, so a zone satisfied only by
    them would strand the carrier at reschedule time (the packers pass
    the exclusion; same per-carrier-context pattern as SpreadBit)."""

    namespaces: Tuple  # sorted namespace scope of the term
    items: Tuple  # canonical selector requirements
    allowed_zones: Tuple  # sorted zone values hosting a qualifying match


@dataclasses.dataclass(frozen=True)
class SpreadBit:
    """Pseudo-taint for one hard topologySpreadConstraint CARRIER
    CONTEXT: the set of topology domains a specific moving pod may not
    enter without exceeding its maxSkew, precomputed from this tick's
    per-domain match counts (``compute_spread_bit``). Set on every spot
    node that lacks the topology key (PodTopologySpread filters such
    nodes) or whose domain is in ``refused``; only the carrier fails to
    tolerate it.

    Like PodAffinityBit, the node side depends on per-tick cluster
    state (match counts), not node properties alone — the packers
    evaluate it outside any label-keyed cache. Two carriers whose
    contexts produce the same (topology_key, refused) verdict share one
    bit harmlessly. What static verdicts cannot prove is two in-plan
    movers involved with one spread identity (their placements shift
    each other's counts) — ``spread_lane_guard`` conservatively kills
    those lanes, exactly like the zone guard."""

    topology_key: str
    refused: Tuple  # sorted domain values the carrier may not enter


@dataclasses.dataclass(frozen=True)
class UnplaceableBit:
    """Pseudo-taint carried by every node; only pods with unmodeled
    constraints fail to tolerate it."""


def selector_universe(pods: Sequence[PodSpec]) -> List[Tuple[str, str]]:
    """Sorted distinct (key, value) pairs across the pods' nodeSelectors —
    the deterministic pseudo-taint universe both packers must share."""
    return sorted({(k, v) for p in pods for k, v in p.node_selector.items()})


def node_affinity_universe(pods: Sequence[PodSpec]) -> List[Tuple]:
    """Sorted distinct canonical required-node-affinity terms across the
    pods — the NodeAffinityBit universe both packers must share."""
    return sorted({p.node_affinity for p in pods if p.node_affinity})


def pod_affinity_universe(pods: Sequence[PodSpec]) -> List[Tuple]:
    """Sorted distinct positive-affinity TERMS across the pods — the
    PodAffinityBit universe both packers must share. A pod's own terms
    live directly in ``pod.pod_affinity_match`` (round-5 canonical
    form)."""
    return sorted({t for p in pods for t in p.pod_affinity_match})


def hosts_affinity_match(
    residents: Sequence[PodSpec], namespaces: Tuple, items: Tuple
) -> bool:
    """Does any resident pod fall in the term's namespace scope and
    match its selector? The node-side evaluation of PodAffinityBit."""
    return any(
        term_matches((namespaces, items), p.namespace, p.labels)
        for p in residents
    )


def match_expr(expr: Tuple, labels, node_name: str) -> bool:
    """One NodeSelectorRequirement against a node's labels — semantics of
    k8s.io/apimachinery labels.Requirement.Matches (NotIn/DoesNotExist
    match when the key is absent; Gt/Lt are base-10 integer compares).
    The reserved FieldIn/FieldNotIn operators are matchFields on
    ``metadata.name`` (io/kube.decode_node_affinity) and compare
    ``node_name``, never labels — a label literally named
    "metadata.name" cannot shadow the field."""
    key, op, values = expr
    if op == "FieldIn":
        return node_name in values
    if op == "FieldNotIn":
        return node_name not in values
    v = labels.get(key)
    if op == "In":
        return v is not None and v in values
    if op == "NotIn":
        return v is None or v not in values
    if op == "Exists":
        return v is not None
    if op == "DoesNotExist":
        return v is None
    if op in ("Gt", "Lt"):
        if v is None or len(values) != 1:
            return False
        # Exact strconv.ParseInt parity: Python's int() also accepts
        # '_', whitespace and arbitrary precision, which would deem a
        # node affinity-satisfying when the real scheduler rejects it —
        # the non-conservative direction.
        lv, rv = _parse_int64(v), _parse_int64(values[0])
        if lv is None or rv is None:
            return False
        return lv > rv if op == "Gt" else lv < rv
    return False


def match_node_affinity(terms: Tuple, labels, node_name: str) -> bool:
    """Required node-affinity: OR over terms, AND within a term (empty
    terms tuple = no constraint; decode drops empty terms, which k8s
    defines to match nothing)."""
    if not terms:
        return True
    return any(
        all(match_expr(e, labels, node_name) for e in term) for term in terms
    )


def intern_constraints(
    nodes: Sequence[NodeSpec],
    selector_pairs: Sequence[Tuple[str, str]],
    affinity_terms: Sequence[Tuple] = (),
    pod_affinity_keys: Sequence[Tuple] = (),
    spread_bits: Sequence["SpreadBit"] = (),
    zone_paff_bits: Sequence["ZonePodAffinityBit"] = (),
) -> TaintTable:
    """``intern_taints`` plus the pseudo-taint tail: selector pairs (in
    the given sorted order), node-affinity requirement bits, positive
    pod-affinity bits, spread-verdict bits, zone-pod-affinity verdict
    bits, and the always-present unplaceable bit."""
    base = intern_taints(nodes)
    taints = list(base.taints)
    taints.extend(SelectorBit(k, v) for k, v in selector_pairs)
    taints.extend(NodeAffinityBit(t) for t in affinity_terms)
    taints.extend(PodAffinityBit(ns, items) for ns, items in pod_affinity_keys)
    taints.extend(spread_bits)
    taints.extend(zone_paff_bits)
    taints.append(UnplaceableBit())
    words = max(1, -(-len(taints) // 32))
    return TaintTable(taints=taints, words=words)


def node_constraint_mask(
    node: NodeSpec, table: TaintTable, residents: Sequence[PodSpec] = ()
) -> np.ndarray:
    """Node-side bits: real hard taints + selector pairs the node lacks +
    affinity requirements the node fails + positive pod-affinity
    selectors no resident matches + the unplaceable bit (always set).
    ``residents`` is the node's model-visible pods this tick (only read
    by PodAffinityBit entries)."""
    mask = np.zeros(table.words, dtype=np.uint32)
    for i, entry in enumerate(table.taints):
        if isinstance(entry, Taint):
            continue  # real taints handled below via the node's own list
        if isinstance(entry, SelectorBit):
            if node.labels.get(entry.key) != entry.value:
                mask[i // 32] |= np.uint32(1 << (i % 32))
        elif isinstance(entry, NodeAffinityBit):
            if not match_node_affinity(entry.terms, node.labels, node.name):
                mask[i // 32] |= np.uint32(1 << (i % 32))
        elif isinstance(entry, PodAffinityBit):
            if not hosts_affinity_match(
                residents, entry.namespaces, entry.items
            ):
                mask[i // 32] |= np.uint32(1 << (i % 32))
        elif isinstance(entry, SpreadBit):
            domain = node.labels.get(entry.topology_key)
            if domain is None or domain in entry.refused:
                mask[i // 32] |= np.uint32(1 << (i % 32))
        elif isinstance(entry, ZonePodAffinityBit):
            zone = node.labels.get(ZONE_LABEL)
            if zone is None or zone not in entry.allowed_zones:
                mask[i // 32] |= np.uint32(1 << (i % 32))
        else:  # UnplaceableBit
            mask[i // 32] |= np.uint32(1 << (i % 32))
    return mask | taint_mask(node.taints, table)


def constraint_mask(
    tolerations: Sequence,
    node_selector,
    unmodeled: bool,
    table: TaintTable,
    node_affinity: Tuple = (),
    pod_affinity: Tuple = (),
    spread_bits: frozenset = frozenset(),
    zone_paff_bits: frozenset = frozenset(),
) -> np.ndarray:
    """Pod-side bits: tolerated real taints + selector pairs the pod does
    NOT require + affinity requirements that are not the pod's own + the
    unplaceable bit unless the pod carries unmodeled constraints.
    ``pod_affinity`` is the pod's own tuple of positive-affinity TERMS
    (``pod.pod_affinity_match``; every term must hold, so the pod fails
    to tolerate each of its terms' bits); ``spread_bits`` the pod's own
    SpreadBit contexts and ``zone_paff_bits`` its own ZonePodAffinityBit
    contexts (every other pod tolerates them)."""
    mask = np.zeros(table.words, dtype=np.uint32)
    for i, entry in enumerate(table.taints):
        if isinstance(entry, Taint):
            ok = any(tol.tolerates(entry) for tol in tolerations)
        elif isinstance(entry, SelectorBit):
            ok = node_selector.get(entry.key) != entry.value
        elif isinstance(entry, NodeAffinityBit):
            ok = entry.terms != node_affinity
        elif isinstance(entry, PodAffinityBit):
            ok = (entry.namespaces, entry.items) not in pod_affinity
        elif isinstance(entry, SpreadBit):
            ok = entry not in spread_bits
        elif isinstance(entry, ZonePodAffinityBit):
            ok = entry not in zone_paff_bits
        else:  # UnplaceableBit
            ok = not unmodeled
        if ok:
            mask[i // 32] |= np.uint32(1 << (i % 32))
    return mask


def taint_mask(taints: Sequence[Taint], table: TaintTable) -> np.ndarray:
    """Bitmask of the hard taints present in ``taints``."""
    mask = np.zeros(table.words, dtype=np.uint32)
    for taint in taints:
        if taint.effect in HARD_EFFECTS:
            i = table.index(taint)
            mask[i // 32] |= np.uint32(1 << (i % 32))
    return mask


def node_taint_mask(node: NodeSpec, table: TaintTable) -> np.ndarray:
    return taint_mask(node.taints, table)


def toleration_mask(tolerations: Sequence, table: TaintTable) -> np.ndarray:
    """Bit t set iff ``tolerations`` tolerate interned taint t."""
    mask = np.zeros(table.words, dtype=np.uint32)
    for i, taint in enumerate(table.taints):
        if any(tol.tolerates(taint) for tol in tolerations):
            mask[i // 32] |= np.uint32(1 << (i % 32))
    return mask


def pod_toleration_mask(pod: PodSpec, table: TaintTable) -> np.ndarray:
    """Bit t set iff the pod tolerates interned taint t."""
    return toleration_mask(pod.tolerations, table)


def affinity_bits(group: str) -> Tuple[int, int]:
    """(word, bit) for an anti-affinity group name (stable hash)."""
    h = int.from_bytes(hashlib.blake2b(group.encode(), digest_size=8).digest(), "little")
    b = h % AFFINITY_BITS
    return b // 32, b % 32


def pod_affinity_mask(pod: PodSpec) -> np.ndarray:
    mask = np.zeros(AFFINITY_WORDS, dtype=np.uint32)
    if pod.anti_affinity_group:
        w, b = affinity_bits(pod.anti_affinity_group)
        mask[w] |= np.uint32(1 << b)
    return mask


def node_affinity_mask(pods: Sequence[PodSpec]) -> np.ndarray:
    """Groups already present on a node (union of its pods' masks)."""
    mask = np.zeros(AFFINITY_WORDS, dtype=np.uint32)
    for pod in pods:
        mask |= pod_affinity_mask(pod)
    return mask


# --- selector-based hostname anti-affinity (the k8s spread pattern) ------
#
# A pod carrying anti-affinity TERMS refuses nodes hosting pods matched
# by any term (within the term's namespace scope), and matched pods
# symmetrically refuse nodes hosting it (what the real scheduler
# enforces for existing pods' required anti-affinity). Encoding: hash
# each distinct term (namespaces + canonical selector) to a bit; a pod's
# affinity mask is its own terms' bits (requirements) OR'd with the bit
# of every universe term that MATCHES the pod (presence). Since the
# same mask is both the fit check and the placement contribution, any
# requirement/presence overlap between two pods forbids co-location —
# exactly the scheduler's symmetric check, over-restricting only in one
# corner (two plain pods both merely *matched* by some third selector,
# or two carriers of one term neither of which matches it), which is
# the safe direction: collisions can only lose a drain, never strand a
# pod.


def match_selector_key(term: Tuple) -> str:
    """Deterministic hash key for a hostname-family term."""
    return term_key(term)


def collect_match_universe(pods) -> List[Tuple]:
    """Sorted distinct hostname anti-affinity terms across the pods —
    deterministic, shared by both packers."""
    return sorted({t for p in pods for t in p.anti_affinity_match})


def match_affinity_mask(
    own_terms: Tuple,
    namespace: str,
    labels,
    universe: Sequence[Tuple],
) -> np.ndarray:
    """Requirement bits (own terms) | presence bits (universe terms
    whose scope covers ``namespace`` and whose selector matches
    ``labels``)."""
    mask = np.zeros(AFFINITY_WORDS, dtype=np.uint32)
    for term in own_terms:
        w, b = affinity_bits(match_selector_key(term))
        mask[w] |= np.uint32(1 << b)
    for term in universe:
        if term_matches(term, namespace, labels):
            w, b = affinity_bits(match_selector_key(term))
            mask[w] |= np.uint32(1 << b)
    return mask


MERGE_TERM_CAP = 16


def merge_affinity_terms(*term_sets: Tuple):
    """AND several canonical required-affinity term sets (each an OR of
    AND-terms) into one canonical OR-of-ANDs, by distribution:
    (A1|A2) & (B1|B2) = A1B1 | A1B2 | A2B1 | A2B2. Used to fold bound
    PersistentVolumes' nodeAffinity into a pod's own requirement
    (models/volumes.py) so the result flows through the existing
    NodeAffinityBit machinery unchanged.

    An empty set means "no constraint" (identity). Returns None when the
    distributed product exceeds MERGE_TERM_CAP terms — the caller treats
    the pod as conservatively unmodeled rather than interning a huge
    requirement."""
    merged: Tuple = ()
    for terms in term_sets:
        if not terms:
            continue
        if not merged:
            merged = terms
            continue
        if len(merged) * len(terms) > MERGE_TERM_CAP:
            return None
        merged = tuple(
            sorted(
                {
                    tuple(sorted(set(a) | set(b)))
                    for a in merged
                    for b in terms
                }
            )
        )
    return merged


# --- zone-topology anti-affinity (static, zone-salted group bits) ---------
#
# Required anti-affinity with topologyKey=topology.kubernetes.io/zone uses
# the SAME requirement|presence hashing as the hostname machinery above,
# but with a zone salt in the key and zone-wide node-side aggregation: a
# spot node's affinity word ORs in the zone masks of every counted pod in
# its entire ZONE — spanning all ready nodes of ANY class, including
# unclassified ones (NodeMap.other / columnar _OTHER): a requirer on a
# control-plane node still repels zone-wide — so a requirer refuses zones hosting a
# match and a matched pod refuses zones hosting a requirer — the
# scheduler's symmetric semantics, statically per tick. What static bits
# CANNOT prove safe is two zone-involved pods inside one candidate lane
# (their in-plan placements could collide zone-wide); the packers mark
# those pods unplaceable (see lane guard in models/tensors.py /
# models/columnar.py). Hash collisions only ever forbid placements — the
# safe direction.

ZONE_LABEL = "topology.kubernetes.io/zone"


def zone_selector_key(term: Tuple) -> str:
    """Hash key for a zone-family term. The \\x1d prefix keeps the zone
    keyspace disjoint from hostname keys (a term_key always starts with
    a namespace name, never a separator byte)."""
    return "\x1dzone" + term_key(term)


def collect_zone_universe(pods) -> List[Tuple]:
    """Sorted distinct zone anti-affinity terms across the pods —
    deterministic, shared by both packers."""
    return sorted({t for p in pods for t in p.anti_affinity_zone_match})


def zone_match_affinity_mask(
    own_terms: Tuple,
    namespace: str,
    labels,
    universe: Sequence[Tuple],
) -> np.ndarray:
    """Requirement bits (own zone terms) | presence bits (universe zone
    terms matching this pod) — the zone-family analog of
    ``match_affinity_mask``."""
    mask = np.zeros(AFFINITY_WORDS, dtype=np.uint32)
    for term in own_terms:
        w, b = affinity_bits(zone_selector_key(term))
        mask[w] |= np.uint32(1 << b)
    for term in universe:
        if term_matches(term, namespace, labels):
            w, b = affinity_bits(zone_selector_key(term))
            mask[w] |= np.uint32(1 << b)
    return mask


def zone_lane_guard(pods: Sequence[PodSpec]) -> set:
    """Slot indices (within one candidate lane) to mark unplaceable.

    For each zone TERM carried by a lane pod: if two or more lane pods
    are involved with it (carry it, or are in its scope and matched by
    its selector), their in-plan placements could collide zone-wide in
    ways the static zone bits cannot see — mark every involved pod,
    which conservatively fails the lane. A single involved pod per term
    is fully covered by the static bits. Shared by both packers so the
    decision is bit-identical."""
    carried: dict = {}
    for i, p in enumerate(pods):
        for term in p.anti_affinity_zone_match:
            carried.setdefault(term, set()).add(i)
    out: set = set()
    for term, involved in carried.items():
        involved = set(involved)
        for i, p in enumerate(pods):
            if term_matches(term, p.namespace, p.labels):
                involved.add(i)
        if len(involved) >= 2:
            out |= involved
    return out


# --- hard topologySpreadConstraints (per-carrier static verdicts) ---------
#
# A hard (DoNotSchedule) spread constraint bounds, for the pod CARRYING
# it at ITS schedule time, the per-domain count of selector-matched pods:
# placing p in domain d must keep count(d) - min-over-domains <= maxSkew.
# Unlike anti-affinity there is no symmetric direction — resident
# carriers never repel incoming pods — so only MOVING carriers need
# modeling. The verdict is computed statically per tick per carrier
# (compute_spread_bit) and interned as a SpreadBit pseudo-taint:
#
# - counts tally selector matches over every model-visible pod (counted
#   pods of both classes + pods on unclassified-ready and NOT-READY
#   nodes — kube-scheduler's default nodeTaintsPolicy=Ignore counts
#   dead nodes' domains and pods), keyed by the node's topology-key
#   value; nodes lacking the key contribute nothing and admit nothing
#   (PodTopologySpread filters them);
# - domains span every visible node's key value, INCLUDING zero-count
#   domains — the min is what makes skew bite;
# - the carrier's own departure is exact: if p itself matches its
#   selector, its source domain's count drops by one, which can lower
#   the global min (stricter) and lowers its own domain's bar by one
#   (the "d == own" offset);
# - domain-eligibility filtering the real scheduler applies
#   (nodeAffinityPolicy=Honor) is deliberately ignored: a min over MORE
#   domains is never larger, so the verdict is only ever stricter —
#   the safe direction. Below-threshold spot pods are invisible here
#   exactly as they are to the reference's own snapshot
#   (nodes/nodes.go:137-141: presumed preemptible).
#
# What the static verdict cannot see is in-plan interaction: a second
# mover involved with the same identity (carrying it or matched by its
# selector) shifts counts mid-plan — spread_lane_guard marks all
# involved slots unplaceable, conservatively failing the lane.


def spread_self_match(pod: PodSpec, items: Tuple) -> bool:
    """Does the carrier match its own selector (Deployment spread does)?
    Only then does its move shift the counts its verdict depends on.
    ``items`` is a canonical requirement selector (round 5 widened to
    the full operator surface)."""
    return selector_matches(items, pod.labels)


def compute_spread_bit(
    topology_key: str,
    max_skew: int,
    own_domain,
    counts,
    all_domains,
    self_match: bool,
) -> "SpreadBit":
    """The refused-domain verdict for one carrier context.

    ``counts``: matching-pod tally per domain (zero-count domains may be
    absent); ``all_domains``: every topology-key value among visible
    ready nodes; ``own_domain``: the carrier's current domain (None when
    its node lacks the key); ``self_match``: does the carrier match its
    own selector (kube-scheduler's selfMatchNum — only then does its
    own move shift counts, and only then does its arrival count).
    Refused(d) ⇔ counts_excl(d) + selfMatch - min_excl > maxSkew, with
    counts_excl the tally after the carrier's departure (kube-scheduler
    computes the same check over existing pods at the re-schedule
    instant, when the carrier has already left its node). No domains at
    all ⇒ nothing to enumerate; keyless nodes are always refused by the
    node-side rule."""
    full = {d: int(counts.get(d, 0)) for d in all_domains}
    if self_match and own_domain is not None and own_domain in full:
        full = {
            d: v - (1 if d == own_domain else 0) for d, v in full.items()
        }
    if not full:
        return SpreadBit(topology_key=topology_key, refused=())
    limit = min(full.values()) + max_skew - (1 if self_match else 0)
    return SpreadBit(
        topology_key=topology_key,
        refused=tuple(sorted(d for d, v in full.items() if v > limit)),
    )


def spread_lane_guard(pods: Sequence[PodSpec]) -> set:
    """Slot indices (within one candidate lane) to mark unplaceable:
    for each spread selector identity carried by a lane pod, if two or
    more lane pods are involved with it (carry it, or are matched by
    it), their in-plan placements shift each other's domain counts in
    ways the static verdicts cannot see. Same shape as
    ``zone_lane_guard``; shared by both packers so the decision is
    bit-identical."""
    carried: dict = {}
    for i, p in enumerate(pods):
        for _, _, items in p.spread_constraints:
            carried.setdefault((p.namespace, items), set()).add(i)
    out: set = set()
    for (ns, items), involved in carried.items():
        involved = set(involved)
        for i, p in enumerate(pods):
            if p.namespace == ns and selector_matches(items, p.labels):
                involved.add(i)
        if len(involved) >= 2:
            out |= involved
    return out


def fit_mask(
    xp,
    *,
    free,  # [..., S, R] remaining capacity
    count,  # [..., S] current pod count
    max_pods,  # [S]
    node_taints,  # [S, W] uint32
    node_ok,  # [S] bool (ready, schedulable, non-padding)
    node_aff,  # [..., S, A] uint32 groups present
    req,  # [..., R] pod request
    tol,  # [..., W] uint32 pod tolerations
    aff,  # [..., A] uint32 pod group mask
):
    """The full per-(pod, spot-node) admissibility mask.

    ``xp`` is ``numpy`` or ``jax.numpy`` — the oracle and the TPU solver
    share this exact predicate definition, which is what the parity tests
    lean on. Leading batch dims of ``free``/``count``/``node_aff`` and of
    the pod operands must broadcast against each other.
    """
    res_ok = xp.all(free >= req[..., None, :], axis=-1)  # [..., S]
    cnt_ok = count < max_pods
    taint_ok = xp.all((node_taints & ~tol[..., None, :]) == 0, axis=-1)
    aff_ok = xp.all((node_aff & aff[..., None, :]) == 0, axis=-1)
    return res_ok & cnt_ok & taint_ok & aff_ok & node_ok


def fit_mask_t(
    xp,
    *,
    free_t,  # [..., R, S] remaining capacity, S minor
    count,  # [..., S]
    max_pods,  # [S]
    node_taints_t,  # [W, S] uint32
    node_ok,  # [S] bool
    node_aff_t,  # [..., A, S] uint32
    req,  # [..., R]
    tol,  # [..., W]
    aff,  # [..., A]
):
    """``fit_mask`` with the spot axis minor.

    Device solvers keep their big carries as [..., R, S]/[..., A, S]: on
    TPU the minor dimension is tiled to 128 lanes, so a minor axis of
    R=2 would pad 64x in HBM (observed: a [C, S, 2] carry ballooned to
    12.5 GB). Semantics are identical to ``fit_mask`` — the randomized
    oracle-parity suites pin the two together.
    """
    res_ok = xp.all(free_t >= req[..., :, None], axis=-2)  # [..., S]
    cnt_ok = count < max_pods
    taint_ok = xp.all((node_taints_t & ~tol[..., :, None]) == 0, axis=-2)
    aff_ok = xp.all((node_aff_t & aff[..., :, None]) == 0, axis=-2)
    return res_ok & cnt_ok & taint_ok & aff_ok & node_ok
