"""Fused Pallas TPU kernel for the batched first-fit solver.

Why a kernel: the XLA ``lax.scan`` version (solver/ffd.py) re-reads and
re-writes the whole [C, S, ·] capacity carry from HBM on every one of the
K scan steps (~200 MB × K of traffic at north-star scale). This kernel
grids over **blocks of candidate lanes** and keeps each block's mutable
state — free capacity, pod counts, affinity occupancy — in VMEM scratch
across *all* K pod placements: HBM sees the spot pool once on the way in
and the results once on the way out.

Layout notes (pallas_guide: last dim = 128 lanes):
- the wide axis S (spot nodes) is the lane dimension of every big
  operand: state is [R, S] / [Cb, S] / [A, S] per lane-block, padded to a
  multiple of 128 by the caller (models/tensors._pad_dim pads to 128
  above 128; below that the kernel pads internally);
- "first fit in probe order" = min over S of (iota where fit) — identical
  to the scan solver's argmax-of-bool, which is what makes this kernel
  bit-compatible with the serial reference semantics
  (rescheduler.go:334-370); parity is enforced by tests.

Semantics contract: identical results to solver/numpy_oracle.plan_oracle
for any PackedCluster.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.solver.carry import (
    CarryLayout,
    NARROW_LAYOUT,
    plane_bytes,
)
from k8s_spot_rescheduler_tpu.solver.result import SolveResult

_BIG = 2**30  # python int: jnp constants would be captured by the kernel
LANE_BLOCK = 128  # candidate lanes per grid step (TPU lane width)

# Mosaic's scoped-vmem budget; past this the kernel cannot hold a lane
# block's state on chip (observed failure at S=5120: 23.3M > 16M).
_VMEM_BUDGET = 14 * 2**20


def _footprint_per_spot(C: int, R: int, A: int) -> int:
    """Per-spot-column VMEM bytes of one lane block: scratch (R+A+1
    planes of [Cb, S] i32) plus ~4 live temporaries. The single source
    of truth for both the fallback guard and the chunk sizing."""
    return min(LANE_BLOCK, C) * 4 * (R + A + 5)


def needs_scan_fallback(C: int, S: int, R: int, A: int) -> bool:
    """True when the per-block VMEM footprint would exceed the budget;
    the caller then chunks the spot axis (first-fit) or uses the HBM
    scan solver (best-fit; same semantics)."""
    return _footprint_per_spot(C, R, A) * S > _VMEM_BUDGET


def _stream_footprint_per_spot(
    C: int, R: int, A: int, layout: CarryLayout
) -> int:
    """Per-spot-column VMEM bytes of one lane block of the FUSED
    best-fit stream kernel: the narrow delta carry planes
    (solver/carry.plane_bytes — layout.used x R + layout.count +
    layout.aff x A per lane, delta-form so no static copies) plus ~6
    live 32-bit temporaries (fit / widened free / widened count / slack
    / masked iota / onehot)."""
    return min(LANE_BLOCK, C) * (plane_bytes(layout, R, A) + 4 * 6)


def needs_stream_fallback(
    C: int, S: int, R: int, A: int, layout: CarryLayout
) -> bool:
    """True when the fused stream kernel's narrow resident carry would
    not fit VMEM; the caller then runs the XLA carry-streamed scan
    (solver/ffd.plan_ffd_streamed, best_fit) — same semantics."""
    return _stream_footprint_per_spot(C, R, A, layout) * S > _VMEM_BUDGET


def _kernel(
    # inputs (VMEM refs). Slot tensors carry the pod-slot axis K as the
    # LEADING (untiled) dim: Mosaic only allows dynamic indexing there.
    slot_req_ref,  # f32 [K, R, Cb]
    slot_valid_ref,  # i32 [K, 1, Cb]
    slot_tol_ref,  # u32 [K, W, Cb]
    slot_aff_ref,  # u32 [K, A, Cb]
    cand_valid_ref,  # i32 [Cb, 1]
    spot_free_ref,  # f32 [R, S]
    spot_count_ref,  # i32 [1, S]
    spot_maxp_ref,  # i32 [1, S]
    spot_taints_ref,  # u32 [W, S]
    spot_ok_ref,  # i32 [1, S]
    spot_aff_ref,  # u32 [A, S]
    # outputs
    feasible_ref,  # i32 [Cb, 1]
    chosen_ref,  # i32 [K, 1, Cb]
    # scratch
    free,  # f32 [R, Cb, S]
    count,  # i32 [Cb, S]
    aff,  # u32 [A, Cb, S]
    feas,  # i32 [Cb, 1]
    *,
    K: int,
    R: int,
    W: int,
    A: int,
    best_fit: bool,
):
    Cb, S = count.shape

    # init per-lane state from the shared spot pool
    for r in range(R):
        free[r] = jnp.broadcast_to(spot_free_ref[r][None, :], (Cb, S))
    count[...] = jnp.broadcast_to(spot_count_ref[0][None, :], (Cb, S))
    for a in range(A):
        aff[a] = jnp.broadcast_to(spot_aff_ref[a][None, :], (Cb, S))
    feas[...] = cand_valid_ref[...]

    iota = jax.lax.broadcasted_iota(jnp.int32, (Cb, S), 1)
    cnt_cap = jnp.broadcast_to(spot_maxp_ref[0][None, :], (Cb, S))
    node_ok = jnp.broadcast_to(spot_ok_ref[0][None, :], (Cb, S)) != 0

    # Dynamic trip count: only iterate up to the last valid pod slot in
    # this lane block. Candidates are packed in drain-priority order, so
    # whole blocks of empty/invalid lanes (no evictable pods) reduce to
    # zero placement steps — at north-star scale this skips ~60% of the
    # static K·blocks work. Slots past kmax would be no-ops anyway
    # (place=0, feas factor 1), so this is bit-exact.
    valid_k = slot_valid_ref[...]  # i32 [K, 1, Cb]
    iota_k = jax.lax.broadcasted_iota(jnp.int32, valid_k.shape, 0)
    kmax = jnp.max(jnp.where(valid_k != 0, iota_k + 1, 0))
    chosen_ref[...] = jnp.full_like(chosen_ref[...], -1)

    def body(k, _):
        # pod slot k of every lane in the block
        fit = node_ok
        for r in range(R):
            req_r = slot_req_ref[k, r][:, None]  # [Cb, 1]
            fit &= free[r] >= req_r
        fit &= count[...] < cnt_cap
        for w in range(W):
            tol_w = slot_tol_ref[k, w][:, None].astype(jnp.uint32)
            taints_w = jnp.broadcast_to(
                spot_taints_ref[w][None, :], (Cb, S)
            ).astype(jnp.uint32)
            fit &= (taints_w & ~tol_w) == 0
        for a in range(A):
            aff_a = slot_aff_ref[k, a][:, None].astype(jnp.uint32)
            fit &= (aff[a] & aff_a) == 0

        if best_fit:
            # tightest primary-resource fit; slack values are integral in
            # f32, so the equality re-scan is exact (ties -> probe order)
            req_0 = slot_req_ref[k, 0][:, None]
            slack = jnp.where(fit, free[0] - req_0, jnp.float32(3e38))
            min_slack = jnp.min(slack, axis=1, keepdims=True)
            masked = jnp.where(fit & (slack == min_slack), iota, _BIG)
        else:
            masked = jnp.where(fit, iota, _BIG)
        first = jnp.min(masked, axis=1, keepdims=True)  # i32 [Cb, 1]
        # Mosaic note: all size-1-minor-dim values stay 32-bit — inserting
        # or broadcasting a minor dim of an i1 is unsupported on TPU.
        anyfit_i = jnp.where(first < _BIG, 1, 0)  # i32 [Cb, 1]
        valid_i = slot_valid_ref[k, 0][:, None]  # i32 [Cb, 1]
        place_i = valid_i * anyfit_i  # i32 [Cb, 1]
        place_s = jnp.broadcast_to(place_i, (Cb, S)) != 0  # [Cb, S]

        onehot = (iota == first) & place_s  # [Cb, S]
        for r in range(R):
            req_r = slot_req_ref[k, r][:, None]
            free[r] = jnp.where(onehot, free[r] - req_r, free[r])
        count[...] = count[...] + onehot.astype(jnp.int32)
        for a in range(A):
            aff_a = slot_aff_ref[k, a][:, None].astype(jnp.uint32)
            aff[a] = jnp.where(onehot, aff[a] | aff_a, aff[a])

        # feasible &= any_fit | ~valid  (in i32 arithmetic)
        feas[...] = feas[...] * jnp.maximum(anyfit_i, 1 - valid_i)
        chosen_ref[k] = jnp.where(place_i != 0, first, -1).reshape(1, Cb)
        return 0

    jax.lax.fori_loop(0, kmax, body, 0)
    feasible_ref[...] = feas[...]


def _stream_kernel(
    # inputs — identical layout to _kernel (K leading/untiled on slots)
    slot_req_ref,  # f32 [K, R, Cb]
    slot_valid_ref,  # i32 [K, 1, Cb]
    slot_tol_ref,  # u32 [K, W, Cb]
    slot_aff_ref,  # u32 [K, A, Cb]
    cand_valid_ref,  # i32 [Cb, 1]
    spot_free_ref,  # f32 [R, S]
    spot_count_ref,  # i32 [1, S]
    spot_maxp_ref,  # i32 [1, S]
    spot_taints_ref,  # u32 [W, S]
    spot_ok_ref,  # i32 [1, S]
    spot_aff_ref,  # u32 [A, S]
    # outputs
    feasible_ref,  # i32 [Cb, 1]
    chosen_ref,  # i32 [K, 1, Cb]
    # scratch — the NARROW delta carry, resident across all K steps
    used,  # layout.used [R, Cb, S] — capacity consumed
    dcount,  # layout.count [Cb, S] — placements added
    daff,  # layout.aff [A, Cb, S] — placed pods' aff bits
    feas,  # i32 [Cb, 1]
    *,
    K: int,
    R: int,
    W: int,
    A: int,
):
    """Fused elect-then-commit best-fit stream step (solver/ffd
    ``_stream_bf_step``), one kernel for all K placements.

    The XLA streamed best-fit path holds THREE stacked copies of the
    chunk state per step (the scanned delta carry, the widened
    absolutes, and the [Cb, S]-broadcast statics the wide ``_kernel``
    materializes in scratch). Here the resident state is ONLY the
    delta carry in the narrow ``CarryLayout`` dtypes: the statics stay
    in their input refs and are widened against the deltas in
    registers at each step (widen-on-read, exactly solver/ffd._widen),
    then the elected placement narrows back on store.

    Bit-identity argument: ``_stream_bf_step``'s per-chunk min/argmin
    plus strict-< lexicographic (slack, chunk-order) election IS the
    global first-minimum argmin over the full spot axis — so one fused
    election over full S (min slack, then first index attaining it,
    the ``_kernel`` best-fit idiom) reproduces the streamed scan's
    placements for EVERY carry_chunks value, and plan_ffd(best_fit)'s,
    and the host oracle's. Pinned by tests/test_pallas.py across
    multiple chunk counts."""
    Cb, S = dcount.shape

    used[...] = jnp.zeros(used.shape, used.dtype)
    dcount[...] = jnp.zeros(dcount.shape, dcount.dtype)
    daff[...] = jnp.zeros(daff.shape, daff.dtype)
    feas[...] = cand_valid_ref[...]

    iota = jax.lax.broadcasted_iota(jnp.int32, (Cb, S), 1)

    # dynamic trip count, exactly _kernel: slots past the last valid
    # one are no-ops (place=0, feas factor 1), so stopping at kmax is
    # bit-exact
    valid_k = slot_valid_ref[...]  # i32 [K, 1, Cb]
    iota_k = jax.lax.broadcasted_iota(jnp.int32, valid_k.shape, 0)
    kmax = jnp.max(jnp.where(valid_k != 0, iota_k + 1, 0))
    chosen_ref[...] = jnp.full_like(chosen_ref[...], -1)

    def body(k, _):
        # widen-on-read: absolute views = statics (input refs, never
        # copied to scratch) combined with the narrow deltas
        fit = jnp.broadcast_to(spot_ok_ref[0][None, :], (Cb, S)) != 0
        free_0 = None
        req_0 = None
        for r in range(R):
            req_r = slot_req_ref[k, r][:, None]  # [Cb, 1]
            free_r = jnp.broadcast_to(
                spot_free_ref[r][None, :], (Cb, S)
            ) - used[r].astype(jnp.float32)
            fit &= free_r >= req_r
            if r == 0:
                free_0, req_0 = free_r, req_r
        count_w = jnp.broadcast_to(
            spot_count_ref[0][None, :], (Cb, S)
        ) + dcount[...].astype(jnp.int32)
        fit &= count_w < jnp.broadcast_to(spot_maxp_ref[0][None, :], (Cb, S))
        for w in range(W):
            tol_w = slot_tol_ref[k, w][:, None].astype(jnp.uint32)
            taints_w = jnp.broadcast_to(
                spot_taints_ref[w][None, :], (Cb, S)
            ).astype(jnp.uint32)
            fit &= (taints_w & ~tol_w) == 0
        for a in range(A):
            aff_a = slot_aff_ref[k, a][:, None].astype(jnp.uint32)
            aff_w = jnp.broadcast_to(
                spot_aff_ref[a][None, :], (Cb, S)
            ) | daff[a].astype(jnp.uint32)
            fit &= (aff_w & aff_a) == 0

        # elect: tightest primary-resource fit; slack values are
        # integral in f32, so the equality re-scan is exact and the
        # first index attaining the min == the global argmin (ties ->
        # probe order, the _stream_bf_step strict-< election)
        slack = jnp.where(fit, free_0 - req_0, jnp.float32(3e38))
        min_slack = jnp.min(slack, axis=1, keepdims=True)
        masked = jnp.where(fit & (slack == min_slack), iota, _BIG)
        first = jnp.min(masked, axis=1, keepdims=True)  # i32 [Cb, 1]
        anyfit_i = jnp.where(first < _BIG, 1, 0)  # i32 [Cb, 1]
        valid_i = slot_valid_ref[k, 0][:, None]  # i32 [Cb, 1]
        place_i = valid_i * anyfit_i  # i32 [Cb, 1]
        place_s = jnp.broadcast_to(place_i, (Cb, S)) != 0

        # commit: narrow-on-store, exactly the solver/ffd._scan_step
        # delta updates (casts are exact within the layout guard)
        onehot = (iota == first) & place_s  # [Cb, S]
        for r in range(R):
            req_r = slot_req_ref[k, r][:, None]
            used[r] = used[r] + (onehot * req_r).astype(used.dtype)
        dcount[...] = dcount[...] + onehot.astype(dcount.dtype)
        for a in range(A):
            aff_a = slot_aff_ref[k, a][:, None].astype(jnp.uint32)
            daff[a] = daff[a] | jnp.where(
                onehot, aff_a, jnp.uint32(0)
            ).astype(daff.dtype)

        feas[...] = feas[...] * jnp.maximum(anyfit_i, 1 - valid_i)
        chosen_ref[k] = jnp.where(place_i != 0, first, -1).reshape(1, Cb)
        return 0

    jax.lax.fori_loop(0, kmax, body, 0)
    feasible_ref[...] = feas[...]


def plan_ffd_pallas(
    packed: PackedCluster,
    interpret: bool | None = None,
    best_fit: bool = False,
) -> SolveResult:
    """Jittable Pallas solve over a PackedCluster (same contract as
    solver/ffd.plan_ffd). Falls back to interpret mode off-TPU.

    Shapes whose lane-block state exceeds VMEM take the chunked path
    (first-fit; see ``_plan_ffd_chunked``) or the HBM scan solver
    (best-fit, which needs a global tightest-slack election and does not
    decompose over spot chunks)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    C0, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    A = packed.spot_aff.shape[1]

    if needs_scan_fallback(C0, S, R, A):
        if best_fit or interpret or S % 128:
            from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

            return plan_ffd(packed, best_fit=best_fit)
        return _plan_ffd_chunked(packed, interpret)

    feasible, chosen = _invoke_kernel(packed, interpret, best_fit)
    assignment = jnp.where(feasible[:, None], chosen, -1)
    return SolveResult(feasible=feasible, assignment=assignment)


def _plan_ffd_chunked(packed: PackedCluster, interpret: bool) -> SolveResult:
    """First-fit over spot CHUNKS that each fit VMEM.

    First-fit decomposes exactly over an ordered partition of the spot
    axis: per-spot state is independent across chunks and first-fit
    prefers earlier spots, so placing every pod that fits chunk 0 (in
    slot order), then offering the leftovers to chunk 1, and so on,
    reproduces the global first-fit placement pod for pod. The kernel
    already places pods regardless of lane feasibility, so each chunk
    pass is just the kernel with ``slot_valid`` masked to the
    still-unplaced pods; a lane is feasible iff nothing remains. (This
    does NOT hold for best-fit — its tightest-slack election is global —
    which keeps the HBM scan fallback.)"""
    C0, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    A = packed.spot_aff.shape[1]
    per_spot = _footprint_per_spot(C0, R, A)
    Sc = max(128, (_VMEM_BUDGET // per_spot) // 128 * 128)

    remaining = jnp.asarray(packed.slot_valid)
    chosen_total = jnp.full((C0, K), -1, jnp.int32)
    for off in range(0, S, Sc):
        end = min(off + Sc, S)
        sub = packed._replace(
            slot_valid=remaining,
            spot_free=packed.spot_free[off:end],
            spot_count=packed.spot_count[off:end],
            spot_max_pods=packed.spot_max_pods[off:end],
            spot_taints=packed.spot_taints[off:end],
            spot_ok=packed.spot_ok[off:end],
            spot_aff=packed.spot_aff[off:end],
        )
        _, chosen_b = _invoke_kernel(sub, interpret, best_fit=False)
        placed_b = chosen_b >= 0
        chosen_total = jnp.where(placed_b, chosen_b + off, chosen_total)
        remaining = remaining & ~placed_b
    feasible = jnp.asarray(packed.cand_valid) & ~jnp.any(remaining, axis=1)
    assignment = jnp.where(feasible[:, None], chosen_total, -1)
    return SolveResult(feasible=feasible, assignment=assignment)


def _invoke_kernel(
    packed: PackedCluster,
    interpret: bool,
    best_fit: bool,
    stream_layout: CarryLayout | None = None,
):
    """One kernel invocation; returns (feasible [C0] bool, chosen [C0, K]
    i32 with -1 for unplaced slots, UNmasked by lane feasibility).
    ``stream_layout`` selects the fused best-fit stream kernel
    (``_stream_kernel``) with its scratch carry in the layout's narrow
    dtypes; the input/output plumbing is shared."""
    slot_req = jnp.asarray(packed.slot_req, jnp.float32)
    C0, K, R = slot_req.shape
    S = packed.spot_free.shape[0]
    W = packed.spot_taints.shape[1]
    A = packed.spot_aff.shape[1]

    # Mosaic requires lane-dim blocks of 128 (or the full axis): small
    # problems run as one block; large ones pad C to a 128 multiple and
    # grid over 128-lane blocks (padding lanes are invalid -> inert).
    if C0 <= LANE_BLOCK:
        C, Cb = C0, C0
    else:
        C = ((C0 + LANE_BLOCK - 1) // LANE_BLOCK) * LANE_BLOCK
        Cb = LANE_BLOCK

    def pad_c(arr, axis=0):
        if C == C0:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, C - C0)
        return jnp.pad(arr, widths)

    grid = (C // Cb,)
    if stream_layout is None:
        kernel = functools.partial(
            _kernel, K=K, R=R, W=W, A=A, best_fit=best_fit
        )
        dt_used, dt_count, dt_aff = jnp.float32, jnp.int32, jnp.uint32
    else:
        kernel = functools.partial(_stream_kernel, K=K, R=R, W=W, A=A)
        dt_used = jnp.dtype(stream_layout.used)
        dt_count = jnp.dtype(stream_layout.count)
        dt_aff = jnp.dtype(stream_layout.aff)

    out_shape = (
        jax.ShapeDtypeStruct((C, 1), jnp.int32),  # feasible
        jax.ShapeDtypeStruct((K, 1, C), jnp.int32),  # chosen
    )
    in_specs = [
        pl.BlockSpec((K, R, Cb), lambda i: (0, 0, i)),
        pl.BlockSpec((K, 1, Cb), lambda i: (0, 0, i)),
        pl.BlockSpec((K, W, Cb), lambda i: (0, 0, i)),
        pl.BlockSpec((K, A, Cb), lambda i: (0, 0, i)),
        pl.BlockSpec((Cb, 1), lambda i: (i, 0)),
        pl.BlockSpec((R, S), lambda i: (0, 0)),
        pl.BlockSpec((1, S), lambda i: (0, 0)),
        pl.BlockSpec((1, S), lambda i: (0, 0)),
        pl.BlockSpec((W, S), lambda i: (0, 0)),
        pl.BlockSpec((1, S), lambda i: (0, 0)),
        pl.BlockSpec((A, S), lambda i: (0, 0)),
    ]
    out_specs = (
        pl.BlockSpec((Cb, 1), lambda i: (i, 0)),
        pl.BlockSpec((K, 1, Cb), lambda i: (0, 0, i)),
    )
    scratch_shapes = [
        pltpu.VMEM((R, Cb, S), dt_used),
        pltpu.VMEM((Cb, S), dt_count),
        pltpu.VMEM((A, Cb, S), dt_aff),
        pltpu.VMEM((Cb, 1), jnp.int32),
    ]

    feasible_i, chosen = pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=out_shape,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(
        pad_c(slot_req, 0).transpose(1, 2, 0),  # [K, R, C]
        pad_c(jnp.asarray(packed.slot_valid, jnp.int32), 0).T[:, None, :],
        pad_c(jnp.asarray(packed.slot_tol, jnp.uint32), 0).transpose(1, 2, 0),
        pad_c(jnp.asarray(packed.slot_aff, jnp.uint32), 0).transpose(1, 2, 0),
        pad_c(jnp.asarray(packed.cand_valid, jnp.int32), 0)[:, None],
        jnp.asarray(packed.spot_free, jnp.float32).T,
        jnp.asarray(packed.spot_count, jnp.int32)[None, :],
        jnp.asarray(packed.spot_max_pods, jnp.int32)[None, :],
        jnp.asarray(packed.spot_taints, jnp.uint32).T,
        jnp.asarray(packed.spot_ok, jnp.int32)[None, :],
        jnp.asarray(packed.spot_aff, jnp.uint32).T,
    )

    feasible = feasible_i[:C0, 0] != 0
    return feasible, chosen[:, 0, :C0].T


plan_ffd_pallas_jit = jax.jit(
    plan_ffd_pallas, static_argnames=("interpret", "best_fit")
)


def plan_stream_bf_pallas(
    packed: PackedCluster,
    *,
    carry_chunks: int = 2,
    layout: CarryLayout = NARROW_LAYOUT,
    interpret: bool | None = None,
) -> SolveResult:
    """Fused best-fit stream solve: the Pallas twin of
    ``solver/ffd.plan_ffd_streamed(best_fit=True)`` (same contract,
    bit-identical results at every ``carry_chunks``).

    The XLA streamed path elects per chunk and commits via a second
    ``lax.map`` over the stacked state — three copies of the chunk
    state live per step. The kernel fuses elect-then-commit with ONLY
    the narrow delta carry resident in VMEM (statics widened from
    their input refs in registers), so HBM sees the spot pool once in
    and the selections once out. ``carry_chunks`` does not change the
    result (the chunked election is provably the global argmin); it
    sizes the XLA fallback taken when the carry exceeds the VMEM
    budget (``needs_stream_fallback``)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    C0, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    A = packed.spot_aff.shape[1]

    if needs_stream_fallback(C0, S, R, A, layout):
        from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd_streamed

        return plan_ffd_streamed(
            packed, carry_chunks=carry_chunks, layout=layout, best_fit=True
        )

    feasible, chosen = _invoke_kernel(
        packed, interpret, best_fit=True, stream_layout=layout
    )
    assignment = jnp.where(feasible[:, None], chosen, -1)
    return SolveResult(feasible=feasible, assignment=assignment)


plan_stream_bf_pallas_jit = jax.jit(
    plan_stream_bf_pallas,
    static_argnames=("carry_chunks", "layout", "interpret"),
)


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr). pallas_call traces abstractly on CPU — the
# kernel body's dtype/width properties are proven without a TPU.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    packed_struct,
)

HOT_PROGRAMS = {
    "pallas.first_fit": HotProgram(
        build=lambda s: (
            functools.partial(plan_ffd_pallas, interpret=True),
            (packed_struct(s),),
        ),
        covers=("ops.pallas_ffd:plan_ffd_pallas",),
    ),
    # the fused best-fit stream kernel behind the pallas carry-streamed
    # union; at MAX_SHAPES the VMEM guard routes the trace through the
    # XLA streamed fallback — the jaxpr auditor then proves the exact
    # program the dispatch would run at that scale
    "pallas.stream_best_fit": HotProgram(
        build=lambda s: (
            functools.partial(plan_stream_bf_pallas, interpret=True),
            (packed_struct(s),),
        ),
        covers=("ops.pallas_ffd:plan_stream_bf_pallas",),
    ),
}
