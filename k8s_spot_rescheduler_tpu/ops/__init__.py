"""Pallas TPU kernels for the solver hot path."""

from k8s_spot_rescheduler_tpu.ops.pallas_ffd import (
    plan_ffd_pallas,
    plan_ffd_pallas_jit,
)

__all__ = ["plan_ffd_pallas", "plan_ffd_pallas_jit"]
