"""Seeded deterministic fault injection over any ClusterClient.

The reference has no way to *test* its failure behavior — its recovery
story ("recompute everything next tick", SURVEY.md §5.3) is asserted,
never exercised. ``FakeCluster`` injects only per-pod eviction-failure
counts (io/fake.py); everything else an apiserver can do to a controller
— flaky LISTs, 429 PDB-blocked evictions, stale reads, dropped watch
streams, a process dying between the taint and the evictions — was
unreproducible. ``ChaosClusterClient`` wraps any ``ClusterClient`` and
replays exactly those failures from a seeded ``FaultPlan``, so every
chaos scenario is deterministic in tests (tests/test_chaos.py) and in
``bench.py --chaos`` / ``--chaos-profile`` on the CLI.

Layering: this sits ABOVE the client (ClusterClient verbs), so it
composes with every backend — fake, polling kube, watch-backed — and
below the control loop, whose degradation paths (skip-tick, planner
fallback, breaker, taint reconciliation) are what the chaos soak proves.
The wrapper deliberately does NOT forward ``columnar_store``: the
vectorized observe path bypasses the read verbs, so chaos forces the
object path where every read passes the fault layer.
"""

from __future__ import annotations

import collections
import dataclasses
import random
from typing import Dict, List, Mapping, Optional

from k8s_spot_rescheduler_tpu.io.cluster import EvictionError
from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeSpec,
    PDBSpec,
    PodSpec,
    Taint,
)
from k8s_spot_rescheduler_tpu.utils import logging as log


class ChaosError(Exception):
    """An injected transient API failure (connection reset / 5xx class)."""


class ChaosInterrupt(BaseException):
    """Simulated process death mid-actuation.

    A ``BaseException`` on purpose: the drain state machine and the
    control loop deliberately survive every ``Exception`` (that is the
    robustness contract under test), so a simulated crash must ride a
    channel none of those guards can swallow. The soak harness catches
    it at top level and "restarts" the controller against the same
    cluster, inheriting whatever residue — an orphaned ``ToBeDeleted``
    taint, half-evicted pods — the crash left behind.
    """


# Read verbs eligible for error-rate / latency / stale-read injection.
_READS = (
    "list_ready_nodes",
    "list_unready_nodes",
    "list_pods_on_node",
    "list_unschedulable_pods",
    "list_pdbs",
    "get_pod",
)
_WRITES = ("evict_pod", "add_taint", "remove_taint")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to break, how often — all draws from one seeded stream.

    - ``error_rates``: per-method probability of raising ``ChaosError``
      (use method names from the ClusterClient surface; reads AND writes).
    - ``latency_s``: per-method injected latency, slept on the wrapper's
      clock before the call (virtual clocks advance instantly).
    - ``fail_n``: per-method "fail the first N calls, then succeed" —
      the deterministic script for retry/backoff tests.
    - ``evict_429``: pod uid -> number of HTTP-429 PDB-blocked eviction
      rejections before the eviction is allowed through.
    - ``stale_read_rate``: probability a list verb returns the PREVIOUS
      successful result for the same query instead of a fresh one.
    - ``watch_drop_rate``: per-event probability a watch stream dies
      with a connection reset (clients with a ``_stream`` hook only).
    - ``watch_stall_rate``: per-stream-open probability the stream is
      OPEN BUT SILENT — it yields nothing until the caller's read
      timeout elapses (slept on the wrapper's clock), then raises the
      same ``TimeoutError`` the wedged socket would. The failure mode
      the client-side watch progress deadline exists to catch: no
      error, no close, no data.
    - ``watch_410_streams``: 1-based stream-open indices that
      immediately deliver a 410-Expired ERROR event and end — the
      scripted "410 right after a resume" that must trigger exactly
      one throttled re-LIST.
    - ``interrupt_on_taint``: 1-based index of the ``add_taint`` call
      that raises ``ChaosInterrupt`` AFTER the taint is applied — the
      canonical mid-drain crash leaving an orphaned taint. 0 = never.

    Mirror corruption (the audit's third chaos scenario) needs no knob
    here: the wrapper sits below the watch stores, so the soak harness
    corrupts a ``ResourceStore`` entry directly and the anti-entropy
    audit must detect and heal it.
    """

    seed: int = 0
    error_rates: Mapping[str, float] = dataclasses.field(default_factory=dict)
    latency_s: Mapping[str, float] = dataclasses.field(default_factory=dict)
    fail_n: Mapping[str, int] = dataclasses.field(default_factory=dict)
    evict_429: Mapping[str, int] = dataclasses.field(default_factory=dict)
    stale_read_rate: float = 0.0
    watch_drop_rate: float = 0.0
    watch_stall_rate: float = 0.0
    watch_410_streams: tuple = ()
    interrupt_on_taint: int = 0

    # the single source for profile names: profile() accepts exactly
    # these, and cli/main.py builds its --chaos-profile choices from it
    PROFILES = ("", "off", "none", "light", "heavy")

    @classmethod
    def profile(cls, name: str, seed: int = 0) -> "FaultPlan":
        """Named presets behind ``--chaos-profile`` (CLI) and
        ``bench.py --chaos``."""
        if name in ("", "off", "none"):
            return cls(seed=seed)
        if name == "light":
            return cls(
                seed=seed,
                error_rates={m: 0.05 for m in _READS},
            )
        if name == "heavy":
            rates = {m: 0.15 for m in _READS}
            rates.update({m: 0.05 for m in _WRITES})
            return cls(
                seed=seed,
                error_rates=rates,
                stale_read_rate=0.05,
                watch_drop_rate=0.10,
            )
        raise ValueError(
            f"unknown chaos profile {name!r} (known: light, heavy)"
        )


class ChaosClusterClient:
    """ClusterClient + EventSink decorator replaying a ``FaultPlan``.

    Deterministic: all probabilistic draws come from one
    ``random.Random(plan.seed)`` stream, so a fixed (plan, call
    sequence) pair always injects the same faults. ``enabled = False``
    quiesces every fault source at once — the soak's "faults clear"
    phase — while scripted counters (``fail_n``/``evict_429``) keep
    their remaining state for when it flips back.
    """

    def __init__(self, inner, plan: FaultPlan, *, clock=None):
        self.inner = inner
        self.plan = plan
        self.clock = clock
        self.enabled = True
        self.rng = random.Random(plan.seed)
        # injected-fault audit: method -> count (tests assert determinism
        # and coverage on this)
        self.stats: collections.Counter = collections.Counter()
        self._fail_n: Dict[str, int] = dict(plan.fail_n)
        self._evict_429: Dict[str, int] = dict(plan.evict_429)
        self._taint_calls = 0
        self._watch_streams = 0
        self._last_read: Dict[tuple, object] = {}

    # --- fault primitives ---

    def _latency(self, method: str) -> None:
        delay = self.plan.latency_s.get(method, 0.0)
        if self.enabled and delay > 0 and self.clock is not None:
            self.clock.sleep(delay)

    def _maybe_fault(self, method: str) -> None:
        """Raise per the scripted fail-N counter or the error rate."""
        if not self.enabled:
            return
        remaining = self._fail_n.get(method, 0)
        if remaining > 0:
            self._fail_n[method] = remaining - 1
            self.stats[method] += 1
            raise ChaosError(f"chaos: scripted failure of {method} "
                             f"({remaining - 1} more)")
        if self.rng.random() < self.plan.error_rates.get(method, 0.0):
            self.stats[method] += 1
            raise ChaosError(f"chaos: injected {method} failure "
                             "(connection reset by peer)")

    def _read(self, method: str, *args):
        """One faulted read: latency, then scripted/random failure, then
        possibly a stale (previous) result, else the fresh one."""
        self._latency(method)
        self._maybe_fault(method)
        key = (method,) + args
        if (
            self.enabled
            and key in self._last_read
            and self.rng.random() < self.plan.stale_read_rate
        ):
            self.stats["stale_read"] += 1
            return self._last_read[key]
        result = getattr(self.inner, method)(*args)
        self._last_read[key] = result
        return result

    # --- read path ---

    def list_ready_nodes(self) -> List[NodeSpec]:
        return self._read("list_ready_nodes")

    def list_unready_nodes(self) -> List[NodeSpec]:
        return self._read("list_unready_nodes")

    def list_pods_on_node(self, node_name: str) -> List[PodSpec]:
        return self._read("list_pods_on_node", node_name)

    def list_unschedulable_pods(self) -> List[PodSpec]:
        return self._read("list_unschedulable_pods")

    def list_pdbs(self) -> List[PDBSpec]:
        return self._read("list_pdbs")

    def get_pod(self, namespace: str, name: str) -> Optional[PodSpec]:
        return self._read("get_pod", namespace, name)

    def _invalidate(self, *keys: tuple) -> None:
        """Read-your-own-writes floor: the apiserver never serves THIS
        client a read older than its own acknowledged write (stale reads
        model cache/replication lag, not time travel past the caller's
        writes). A successful write drops the stale-serving cache for
        the queries it changes — without this, a stale pod LIST can
        resurrect pods the controller itself already evicted and induce
        a phantom double-drain no real apiserver would permit."""
        for key in keys:
            self._last_read.pop(key, None)

    # --- write path ---

    def evict_pod(self, pod: PodSpec, grace_seconds: int) -> None:
        self._latency("evict_pod")
        if self.enabled:
            blocked = self._evict_429.get(pod.uid, 0)
            if blocked > 0:
                self._evict_429[pod.uid] = blocked - 1
                self.stats["evict_429"] += 1
                raise EvictionError(
                    f"chaos: evict {pod.uid}: HTTP 429 Too Many Requests "
                    "(disruption budget exhausted)"
                )
        self._maybe_fault("evict_pod")
        self.inner.evict_pod(pod, grace_seconds)
        self._invalidate(
            ("list_pods_on_node", pod.node_name),
            ("list_unschedulable_pods",),
            ("get_pod", pod.namespace, pod.name),
        )

    def add_taint(self, node_name: str, taint: Taint) -> None:
        self._latency("add_taint")
        self._maybe_fault("add_taint")
        self.inner.add_taint(node_name, taint)
        self._invalidate(("list_ready_nodes",), ("list_unready_nodes",))
        self._taint_calls += 1
        if (
            self.enabled
            and self.plan.interrupt_on_taint
            and self._taint_calls == self.plan.interrupt_on_taint
        ):
            self.stats["interrupt"] += 1
            log.error(
                "chaos: simulating process death right after tainting %s",
                node_name,
            )
            raise ChaosInterrupt(f"chaos: crashed after tainting {node_name}")

    def remove_taint(self, node_name: str, taint_key: str) -> None:
        self._latency("remove_taint")
        self._maybe_fault("remove_taint")
        self.inner.remove_taint(node_name, taint_key)
        self._invalidate(("list_ready_nodes",), ("list_unready_nodes",))

    # --- event sink (never faulted: events are best-effort already) ---

    def event(
        self, kind: str, name: str, event_type: str, reason: str, message: str
    ) -> None:
        self.inner.event(kind, name, event_type, reason, message)

    # --- watch hook (clients with a raw stream, io/kube.py) ---

    def _stream(self, path: str, read_timeout: float = 330.0):
        inner_stream = getattr(self.inner, "_stream")
        self._watch_streams += 1
        stream_no = self._watch_streams
        self._maybe_fault("watch")
        if self.enabled and stream_no in self.plan.watch_410_streams:
            # scripted 410-after-resume: the stream opens fine and
            # immediately reports the resourceVersion expired — the
            # watcher must fall back to exactly one throttled re-LIST
            self.stats["watch_410"] += 1
            yield {
                "type": "ERROR",
                "object": {
                    "kind": "Status", "code": 410, "reason": "Expired",
                    "message": "chaos: scripted resourceVersion expiry",
                },
            }
            return
        if (
            self.enabled
            and self.plan.watch_stall_rate
            and self.rng.random() < self.plan.watch_stall_rate
        ):
            # open-but-silent: no event, no error, no close — exactly
            # what a wedged transport looks like. Sleep out the
            # caller's read timeout on the injected clock (instant on
            # a virtual clock), then raise what the socket would.
            self.stats["watch_stall"] += 1
            if self.clock is not None:
                self.clock.sleep(read_timeout)
            raise TimeoutError(
                "chaos: watch stream open but silent (stalled past the "
                "read timeout)"
            )
        for obj in inner_stream(path, read_timeout):
            yield obj
            if (
                self.enabled
                and self.plan.watch_drop_rate
                and self.rng.random() < self.plan.watch_drop_rate
            ):
                self.stats["watch_drop"] += 1
                raise ConnectionResetError("chaos: watch stream dropped")

    # --- passthrough ---

    def __getattr__(self, name):
        if name == "columnar_store":
            # Refuse the vectorized observe shortcut: it reads the store
            # directly, bypassing every faulted verb — chaos must force
            # the control loop onto the object path.
            raise AttributeError(name)
        return getattr(self.inner, name)
