"""Cluster I/O boundary: client protocol, fake cluster, generators."""

from k8s_spot_rescheduler_tpu.io.cluster import ClusterClient, EventSink, EvictionError
from k8s_spot_rescheduler_tpu.io.fake import FakeCluster

__all__ = ["ClusterClient", "EventSink", "EvictionError", "FakeCluster"]
