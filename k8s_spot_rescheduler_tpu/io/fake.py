"""In-memory simulated cluster.

The unit-test / benchmark / replay "apiserver": holds node and pod state,
serves the read path, and models the write path with injectable failure
counts and termination latency on a virtual clock. It optionally runs a
tiny first-fit scheduler so evicted pods *re-appear* on spot nodes — the
closed-loop behavior the reference relies on the real kube-scheduler for
(README.md:116-123: evicted pods get rescheduled onto the spot pool).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from k8s_spot_rescheduler_tpu.io.cluster import EvictionError
from k8s_spot_rescheduler_tpu.models.cluster import (
    CPU,
    MEMORY,
    PODS,
    NodeSpec,
    PDBSpec,
    PodSpec,
    Taint,
)
from k8s_spot_rescheduler_tpu.predicates.masks import (
    ZONE_LABEL,
    hosts_affinity_match,
    match_node_affinity,
)
from k8s_spot_rescheduler_tpu.predicates.selectors import (
    selector_matches,
    term_matches,
)
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.labels import matches_label


@dataclasses.dataclass
class Event:
    kind: str
    name: str
    event_type: str
    reason: str
    message: str


class FakeCluster:
    """ClusterClient + EventSink implementation over plain dicts."""

    def __init__(
        self,
        clock: Optional[FakeClock] = None,
        *,
        termination_latency: float = 1.0,
        reschedule_evicted: bool = False,
        spot_label: str = "kubernetes.io/role=spot-worker",
    ):
        self.clock = clock or FakeClock()
        self.termination_latency = termination_latency
        self.reschedule_evicted = reschedule_evicted
        self.spot_label = spot_label
        self.nodes: Dict[str, NodeSpec] = {}
        self.pods: Dict[str, PodSpec] = {}  # keyed by namespace/name
        self._by_node: Dict[str, Dict[str, PodSpec]] = {}  # node -> uid -> pod
        self.pdbs: List[PDBSpec] = []
        # volume topology: claims keyed by uid, volumes by name. Pods are
        # resolved against these at add_pod (models/volumes.py) — add
        # PVs/PVCs BEFORE their pods, as a real cluster's bindings
        # pre-date the running pods the planner moves.
        self.pvcs: Dict[str, object] = {}
        self.pvs: Dict[str, object] = {}
        self.events: List[Event] = []
        self.pending: List[PodSpec] = []  # unschedulable (evicted, unplaced)
        # pod uid -> number of eviction calls that must fail first
        self.eviction_failures: Dict[str, int] = {}
        self.evictions: List[str] = []  # audit log of successful evictions
        self._columnar = None  # lazily attached ColumnarStore mirror
        # pod uid -> spot node name: the planner's proven placement for an
        # imminent eviction (DrainPlan.assignments). When set, _schedule
        # tries this node first — standing in for a scheduler that honors
        # the drain plan (the real kube-scheduler re-places pods by its own
        # scoring, README.md:116-123; the quality benchmarks measure
        # *planner* quality, so they route by the proof).
        self.placement_hints: Dict[str, str] = {}

    # --- columnar fast path ---

    def columnar_store(
        self, resources, *, on_demand_label: str, spot_label: str
    ):
        """Attach (or return) the incrementally-maintained columnar mirror
        of this cluster — the control loop's vectorized observe path."""
        from k8s_spot_rescheduler_tpu.models.columnar import ColumnarStore

        store = self._columnar
        if (
            store is None
            or store.resources != tuple(resources)
            or store.on_demand_label != on_demand_label
            or store.spot_label != spot_label
        ):
            store = ColumnarStore(
                resources,
                on_demand_label=on_demand_label,
                spot_label=spot_label,
            )
            for node in self.nodes.values():
                store.add_node(node)
            for pod in self.pods.values():
                store.add_pod(pod)
            self._columnar = store
        return store

    # --- state construction helpers ---

    def add_node(self, node: NodeSpec) -> None:
        self.nodes[node.name] = node
        if self._columnar is not None:
            self._columnar.add_node(node)
        self.retry_pending()

    def add_pod(self, pod: PodSpec) -> None:
        assert pod.node_name in self.nodes, f"unknown node {pod.node_name}"
        if pod.pvc_resolvable:
            from k8s_spot_rescheduler_tpu.models.volumes import (
                resolve_volume_affinity,
            )

            pod = resolve_volume_affinity(pod, self.pvcs, self.pvs)
        stale = self.pods.get(pod.uid)
        self.pods[pod.uid] = pod  # dict upsert: position is preserved
        if stale is not None and stale.node_name != pod.node_name:
            # a re-add under the same uid is a move: one placement only.
            # The production watch path derives its per-node view from
            # the uid-keyed dict, where the upsert kept the pod's global
            # position — rebuild the destination bucket in that order so
            # CPU-tie slot order matches (moves are rare; O(pods)).
            self._by_node.get(stale.node_name, {}).pop(pod.uid, None)
            self._by_node[pod.node_name] = {
                p.uid: p
                for p in self.pods.values()
                if p.node_name == pod.node_name
            }
        else:
            self._by_node.setdefault(pod.node_name, {})[pod.uid] = pod
        if self._columnar is not None:
            self._columnar.add_pod(pod)

    def _remove_pod(self, uid: str) -> Optional[PodSpec]:
        pod = self.pods.pop(uid, None)
        if pod is not None:
            self._by_node.get(pod.node_name, {}).pop(uid, None)
        if self._columnar is not None:
            self._columnar.remove_pod(uid)
        return pod

    def remove_node(self, name: str) -> List[PodSpec]:
        """Spot interruption: the node and its pods vanish; returns the
        displaced pods (the replay harness re-queues them as pending)."""
        self.nodes.pop(name, None)
        displaced = list(self._by_node.pop(name, {}).values())
        for p in displaced:
            self.pods.pop(p.uid, None)
            if self._columnar is not None:
                self._columnar.remove_pod(p.uid)
        if self._columnar is not None:
            self._columnar.remove_node(name)
        return displaced

    # --- read path ---

    def list_ready_nodes(self) -> List[NodeSpec]:
        # reference uses NewReadyNodeLister (rescheduler.go:154): not-ready
        # nodes are invisible to the controller.
        return [n for n in self.nodes.values() if n.ready]

    def list_unready_nodes(self) -> List[NodeSpec]:
        # presence-only visibility (NodeMap.unready): zone/spread counts
        # span these nodes' pods; they are never planning surface
        return [n for n in self.nodes.values() if not n.ready]

    def list_pods_on_node(self, node_name: str) -> List[PodSpec]:
        return list(self._by_node.get(node_name, {}).values())

    def list_unschedulable_pods(self) -> List[PodSpec]:
        return list(self.pending)

    def list_pdbs(self) -> List[PDBSpec]:
        return list(self.pdbs)

    def get_pod(self, namespace: str, name: str) -> Optional[PodSpec]:
        return self.pods.get(f"{namespace}/{name}")

    # --- write path ---

    def evict_pod(self, pod: PodSpec, grace_seconds: int) -> None:
        live = self.pods.get(pod.uid)
        if live is None:
            return  # already gone — eviction succeeds trivially
        remaining = self.eviction_failures.get(pod.uid, 0)
        if remaining > 0:
            self.eviction_failures[pod.uid] = remaining - 1
            raise EvictionError(f"simulated eviction failure for {pod.uid}")
        self.evictions.append(pod.uid)
        # pod terminates after its graceful period (bounded by latency knob)
        delay = min(float(grace_seconds), self.termination_latency)
        self.clock.call_at(self.clock.now() + delay, lambda: self._terminate(pod.uid))

    def _terminate(self, uid: str) -> None:
        pod = self._remove_pod(uid)
        if pod is None:
            return
        if self.reschedule_evicted:
            self._schedule(pod)
            self.retry_pending()

    def retry_pending(self) -> None:
        """Re-attempt placement of unschedulable pods (capacity may have
        appeared since)."""
        if not self.reschedule_evicted or not self.pending:
            return
        waiting, self.pending = self.pending, []
        for pod in waiting:
            self._schedule(pod)

    def _can_place(self, pod: PodSpec, node: NodeSpec) -> bool:
        """The fake scheduler's admission check for one (pod, node) pair —
        the same predicate surface _schedule always enforced."""
        if not matches_label(node.labels, self.spot_label):
            return False
        if not node.ready or node.unschedulable:
            return False
        if any(node.labels.get(k) != v for k, v in pod.node_selector.items()):
            return False
        if not match_node_affinity(pod.node_affinity, node.labels, node.name):
            return False
        hard = [t for t in node.taints if t.effect in ("NoSchedule", "NoExecute")]
        if any(
            not any(tol.tolerates(t) for tol in pod.tolerations) for t in hard
        ):
            return False
        here = self.list_pods_on_node(node.name)
        if len(here) >= node.allocatable.get(PODS, 110):
            return False
        free_cpu = node.allocatable.get(CPU, 0) - sum(
            p.requests.get(CPU, 0) for p in here
        )
        free_mem = node.allocatable.get(MEMORY, 0) - sum(
            p.requests.get(MEMORY, 0) for p in here
        )
        if pod.anti_affinity_group and any(
            p.anti_affinity_group == pod.anti_affinity_group for p in here
        ):
            return False

        # selector anti-affinity, both directions (the scheduler
        # respects existing pods' required anti-affinity too) — round-5
        # widened terms: any term of a's whose scope covers b and whose
        # selector matches b repels
        def _repels(a: PodSpec, b: PodSpec) -> bool:
            return any(
                term_matches(t, b.namespace, b.labels)
                for t in a.anti_affinity_match
            )

        if any(_repels(pod, p) or _repels(p, pod) for p in here):
            return False
        # required positive pod-affinity: the node must already host a
        # match for EVERY term (hostname topology) — the same predicate
        # the packers' PodAffinityBit node side evaluates
        if pod.pod_affinity_match and not all(
            hosts_affinity_match(here, nss, items)
            for nss, items in pod.pod_affinity_match
        ):
            return False
        # zone-topology positive pod-affinity: the node's ZONE must
        # already host a match per term (masks.ZonePodAffinityBit)
        if pod.pod_affinity_zone_match:
            zone_val = node.labels.get(ZONE_LABEL)
            if zone_val is None:
                return False
            zone_pods = [
                q
                for n2 in self.nodes.values()
                if n2.labels.get(ZONE_LABEL) == zone_val
                for q in self.list_pods_on_node(n2.name)
            ]
            if not all(
                hosts_affinity_match(zone_pods, nss, items)
                for nss, items in pod.pod_affinity_zone_match
            ):
                return False
        # zone-topology anti-affinity, both directions, across the whole
        # zone (nodes without the zone label never conflict)
        zone = node.labels.get(ZONE_LABEL)
        if zone is not None:
            def _zone_pods():
                for n2 in self.nodes.values():
                    if n2.labels.get(ZONE_LABEL) == zone:
                        yield from self.list_pods_on_node(n2.name)

            if any(
                term_matches(t, p.namespace, p.labels)
                for p in _zone_pods()
                for t in pod.anti_affinity_zone_match
            ):
                return False
            for p in _zone_pods():
                if any(
                    term_matches(t, pod.namespace, pod.labels)
                    for t in p.anti_affinity_zone_match
                ):
                    return False
        # hard topology-spread (canonical shapes): refuse placements
        # that would exceed maxSkew — kube-scheduler's PodTopologySpread
        # filter over existing pods (the evicted pod is pending, so it
        # is already off its old node here), incl. the selfMatch rule
        for topo, skew, items in pod.spread_constraints:
            d = node.labels.get(topo)
            if d is None:
                return False  # nodes lacking the key are filtered
            counts: Dict[str, int] = {}
            for n2 in self.nodes.values():
                d2 = n2.labels.get(topo)
                if d2 is None:
                    continue
                counts.setdefault(d2, 0)
                for p in self.list_pods_on_node(n2.name):
                    if p.namespace == pod.namespace and selector_matches(
                        items, p.labels
                    ):
                        counts[d2] += 1
            self_m = selector_matches(items, pod.labels)
            if counts[d] + (1 if self_m else 0) - min(counts.values()) > skew:
                return False
        return pod.requests.get(CPU, 0) <= free_cpu and (
            pod.requests.get(MEMORY, 0) <= free_mem
        )

    def _schedule(self, pod: PodSpec) -> None:
        """Minimal kube-scheduler stand-in: the planner's hinted node if one
        is recorded and still admissible, else first spot node with room."""
        if pod.unmodeled_constraints:
            self.pending.append(pod)  # can't reason about it; stays pending
            return
        hint = self.placement_hints.pop(pod.uid, None)
        if hint is not None:
            node = self.nodes.get(hint)
            if node is not None and self._can_place(pod, node):
                self.add_pod(dataclasses.replace(pod, node_name=node.name))
                return
        for node in self.nodes.values():
            if self._can_place(pod, node):
                self.add_pod(dataclasses.replace(pod, node_name=node.name))
                return
        self.pending.append(pod)

    def add_taint(self, node_name: str, taint: Taint) -> None:
        from k8s_spot_rescheduler_tpu.models.cluster import (
            parse_rescheduler_taint_value,
        )

        node = self.nodes[node_name]
        if taint in node.taints:
            return
        for t in node.taints:
            # mirror KubeClusterClient.add_taint: a same-key entry we
            # own is replaced (re-drains refresh the ownership stamp),
            # a FOREIGN same-key entry (CA's scale-down marker) is kept
            # untouched — taint keys are unique per node, and stealing
            # CA's would let the orphan sweep later strip it
            if t.key == taint.key and t.value and (
                parse_rescheduler_taint_value(t.value) is None
            ):
                return
        # REPLACE the list, never mutate in place: the columnar store's
        # per-row mask cache keys on the taint list's identity
        # (models/columnar._spot_taint_rows), exactly like the real
        # kube/watch paths deliver fresh objects
        node.taints = [t for t in node.taints if t.key != taint.key] + [taint]

    def remove_taint(self, node_name: str, taint_key: str) -> None:
        node = self.nodes.get(node_name)
        if node:
            node.taints = [t for t in node.taints if t.key != taint_key]

    # --- event sink ---

    def event(
        self, kind: str, name: str, event_type: str, reason: str, message: str
    ) -> None:
        self.events.append(Event(kind, name, event_type, reason, message))
