"""The cluster-communication boundary.

The reference's "distributed backend" is the Kubernetes apiserver reached
through client-go: watch-cache listers for ready nodes / PDBs /
unschedulable pods (reference rescheduler.go:154-156), per-node pod LISTs
(nodes/nodes.go:129-145), the eviction subresource and taint updates
(scaler/scaler.go:58, 77) and the event sink (rescheduler.go:327-332).
``ClusterClient`` is that surface as one protocol; implementations:

- ``io.fake.FakeCluster`` — in-memory simulated cluster (descendant of the
  reference tests' ``fake.Clientset`` reactor, nodes/nodes_test.go:424-449)
  used by unit tests, the replay harness and the benchmarks;
- a real-cluster shim (kube API over HTTPS) plugs in behind the same
  protocol without touching loop/planner/actuator code.
"""

from __future__ import annotations

from typing import List, Optional, Protocol

from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeSpec,
    PDBSpec,
    PodSpec,
    Taint,
)


class EvictionError(Exception):
    """A pod eviction was rejected (apiserver error / PDB enforcement)."""


class EventSink(Protocol):
    """k8s Event recorder equivalent (reference rescheduler.go:327-332)."""

    def event(
        self, kind: str, name: str, event_type: str, reason: str, message: str
    ) -> None: ...


class ClusterClient(Protocol):
    # --- read path (lister equivalents) ---
    def list_ready_nodes(self) -> List[NodeSpec]: ...
    def list_pods_on_node(self, node_name: str) -> List[PodSpec]: ...
    def list_unschedulable_pods(self) -> List[PodSpec]: ...
    def list_pdbs(self) -> List[PDBSpec]: ...
    def get_pod(self, namespace: str, name: str) -> Optional[PodSpec]: ...

    # --- write path (actuation) ---
    def evict_pod(self, pod: PodSpec, grace_seconds: int) -> None: ...
    def add_taint(self, node_name: str, taint: Taint) -> None: ...
    def remove_taint(self, node_name: str, taint_key: str) -> None: ...
