"""Lease-based leader election for HA deployments.

The reference's Deployment runs **2 replicas** (deploy/deployment.yaml)
but its leader election was removed — the comment at reference
rescheduler.go:139 ("This is where the leader election used to be") and
the orphaned endpoints RBAC rule (deploy/clusterrole.yaml) are all that
remain, so both replicas plan and drain concurrently. This module restores
the missing piece the modern way: a ``coordination.k8s.io/v1`` Lease,
the same primitive client-go's leaderelection package uses today.

Semantics follow client-go's resourcelock loop, tick-driven instead of
threaded (the control loop calls :meth:`ensure` at the top of every
housekeeping tick, reference cadence 10 s):

- expiry is judged from **local observation time** — the instant *we* saw
  the holder's record last change — never by comparing another process's
  wall-clock timestamp against ours (clock-skew safety, the same rule
  client-go applies);
- every mutation is a compare-and-swap on ``metadata.resourceVersion``;
  losing the race (409 Conflict) means following, not crashing;
- a fresh takeover increments ``leaseTransitions`` and resets
  ``acquireTime``.

The wall-clock timestamps written into the Lease (``renewTime`` etc.) are
informational for ``kubectl describe`` parity; correctness never reads
them back.
"""

from __future__ import annotations

import datetime
import os
import socket
import threading
import time
import urllib.error
import uuid
from typing import Optional

from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils.clock import Clock, RealClock

DEFAULT_LEASE_NAME = "k8s-spot-rescheduler-tpu"
DEFAULT_LEASE_NAMESPACE = "kube-system"
# client-go leaderelection defaults
DEFAULT_LEASE_DURATION = 15.0
# background renew cadence as a fraction of the lease duration —
# client-go's retryPeriod:leaseDuration ratio (2s : 15s)
RENEW_FRACTION = 2.0 / 15.0


def default_identity() -> str:
    return f"{socket.gethostname()}_{os.getpid()}_{uuid.uuid4().hex[:8]}"


def _micro_time(epoch: float) -> str:
    return (
        datetime.datetime.fromtimestamp(epoch, datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%fZ")
    )


class LeaseElector:
    """Tick-driven leader election over a coordination.k8s.io Lease.

    ``client`` only needs the private ``_request`` plumbing of
    ``KubeClusterClient`` (GET/POST/PUT with JSON bodies raising
    ``urllib.error.HTTPError`` on failure).
    """

    def __init__(
        self,
        client,
        *,
        identity: str = "",
        name: str = DEFAULT_LEASE_NAME,
        namespace: str = DEFAULT_LEASE_NAMESPACE,
        lease_duration: float = DEFAULT_LEASE_DURATION,
        clock: Optional[Clock] = None,
        wall=time.time,
    ) -> None:
        self.client = client
        self.identity = identity or default_identity()
        self.name = name
        self.namespace = namespace
        self.lease_duration = float(lease_duration)
        self.clock = clock or RealClock()
        self.wall = wall
        self.is_leader = False
        # local-observation record for skew-safe expiry
        self._observed_spec: Optional[dict] = None
        self._observed_at: float = 0.0
        # ensure() may be called from both the control loop and the
        # background renew thread
        self._lock = threading.Lock()
        self._bg: Optional[threading.Thread] = None
        self._bg_stop = threading.Event()

    # --- API plumbing ---

    @property
    def _path(self) -> str:
        return (
            f"/apis/coordination.k8s.io/v1/namespaces/{self.namespace}"
            f"/leases/{self.name}"
        )

    def _get(self) -> Optional[dict]:
        try:
            # no transport-level retries: the elector's own renew cadence
            # IS its retry policy (ensure() demotes on error and recovers
            # next tick, like client-go leaderelection), and backoff
            # sleeps inside a renew would eat into the lease deadline
            return self.client._request("GET", self._path, retries=False)
        except urllib.error.HTTPError as err:
            if err.code == 404:
                return None
            raise

    def _create(self) -> bool:
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": self._my_spec(transitions=0, fresh_acquire=True),
        }
        try:
            self.client._request(
                "POST",
                f"/apis/coordination.k8s.io/v1/namespaces/{self.namespace}/leases",
                body,
            )
            return True
        except urllib.error.HTTPError as err:
            if err.code == 409:  # someone else created it first
                return False
            raise

    def _update(self, lease: dict, spec: dict) -> bool:
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                # CAS: stale resourceVersion -> 409 -> we lost the race
                "resourceVersion": lease.get("metadata", {}).get(
                    "resourceVersion", ""
                ),
            },
            "spec": spec,
        }
        try:
            self.client._request("PUT", self._path, body)
            return True
        except urllib.error.HTTPError as err:
            if err.code == 409:
                return False
            raise

    def _my_spec(self, transitions: int, fresh_acquire: bool,
                 prev: Optional[dict] = None) -> dict:
        now = _micro_time(self.wall())
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration),
            "acquireTime": now if fresh_acquire else (
                (prev or {}).get("acquireTime", now)
            ),
            "renewTime": now,
            "leaseTransitions": transitions,
        }

    # --- the per-tick step ---

    def ensure(self) -> bool:
        """Acquire or renew leadership; returns whether this process may
        act this tick. Never raises on HTTP errors: any apiserver trouble
        demotes to follower (safe: a non-leader only skips work, matching
        the loop's level-triggered per-tick error handling)."""
        with self._lock:
            try:
                self.is_leader = self._ensure()
            except Exception as err:  # noqa: BLE001, exception-discipline — demotion IS the recorded outcome: is_leader flips false, the loop stands by, and the single-attempt lease read's failure already surfaced through the kube layer
                log.vlog(2, "leader election: demoted on error: %s", err)
                self.is_leader = False
            return self.is_leader

    # --- background renewal ---
    #
    # A tick can far outlast the lease: a drain blocks in the eviction
    # verify poll for up to pod_eviction_timeout (minutes), and a leader
    # that only renews at tick boundaries would go quiet mid-drain,
    # letting a standby take over and double-drain — the exact failure
    # the election exists to prevent. client-go renews from a background
    # goroutine for the same reason; so do we. The control loop reads
    # ``is_leader`` (kept fresh by this thread) at each tick boundary.

    def start_background(self, retry_period: Optional[float] = None) -> None:
        period = retry_period or self.lease_duration * RENEW_FRACTION
        self._bg_stop.clear()
        self._bg = threading.Thread(
            target=self._bg_loop, args=(period,),
            name="lease-renew", daemon=True,
        )
        self._bg.start()

    def stop_background(self) -> None:
        self._bg_stop.set()
        if self._bg is not None:
            self._bg.join(timeout=5)
            self._bg = None

    def _bg_loop(self, period: float) -> None:
        while not self._bg_stop.is_set():
            self.ensure()
            self._bg_stop.wait(period)

    def _ensure(self) -> bool:
        lease = self._get()
        if lease is None:
            if self._create():
                log.info("leader election: acquired lease %s/%s",
                         self.namespace, self.name)
                return True
            return False

        spec = lease.get("spec", {}) or {}
        holder = spec.get("holderIdentity", "")
        transitions = int(spec.get("leaseTransitions", 0) or 0)

        if holder == self.identity:
            # renew; a 409 means another replica stole it between our GET
            # and PUT — follow.
            renewed = self._update(
                lease, self._my_spec(transitions, fresh_acquire=False,
                                     prev=spec)
            )
            if not renewed:
                log.info("leader election: lost lease %s/%s on renew",
                         self.namespace, self.name)
            return renewed

        # another process holds the lease: judge expiry by when *we* last
        # observed the record change, not by its embedded timestamps.
        observed_key = {
            k: spec.get(k) for k in ("holderIdentity", "renewTime",
                                     "leaseTransitions")
        }
        if observed_key != self._observed_spec:
            self._observed_spec = observed_key
            self._observed_at = self.clock.now()
            return False
        duration = float(spec.get("leaseDurationSeconds")
                         or self.lease_duration)
        if self.clock.now() < self._observed_at + duration:
            return False
        # holder went quiet for a full lease duration: take over
        took = self._update(
            lease, self._my_spec(transitions + 1, fresh_acquire=True)
        )
        if took:
            log.info(
                "leader election: took lease %s/%s from quiet holder %s",
                self.namespace, self.name, holder,
            )
        return took
