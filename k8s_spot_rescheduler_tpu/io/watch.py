"""Watch-backed cluster caches — the reference's lister equivalents.

The reference never LISTs the whole cluster on its hot path: it builds
three watch-cache listers at startup (reference rescheduler.go:154-156,
``NewReadyNodeLister`` / ``NewPodDisruptionBudgetLister`` /
``NewUnschedulablePodLister``) and every per-tick read hits the local
cache that a background watch stream keeps current. ``KubeClusterClient``
(io/kube.py) approximates that with one full LIST per tick — correct, but
at north-star scale (50k pods) each tick re-transfers the entire pod set.

This module is the faithful equivalent: per-resource background watchers
following the standard Kubernetes list-then-watch protocol —

1. LIST to seed the store and learn ``metadata.resourceVersion``;
2. WATCH from that version with ``allowWatchBookmarks`` — apply
   ADDED/MODIFIED/DELETED incrementally, advance the version on BOOKMARK;
3. on 410 Gone (version expired from etcd) or any stream error, re-LIST
   and resume — the store is level-triggered, never wedged.

``WatchingKubeClusterClient`` serves the ``ClusterClient`` read path from
these stores. Each housekeeping tick gets one *consistent snapshot*: the
first read of a tick (``list_unschedulable_pods``, the loop's safety gate)
freezes the live stores into a per-tick view, so a tick never sees a pod
on two nodes because an event arrived mid-tick. Writes (evictions, taints,
events) pass through to the underlying client unchanged.
"""

from __future__ import annotations

import socket
import threading
import urllib.error
from typing import Callable, Dict, List, Optional, Tuple

from k8s_spot_rescheduler_tpu.io.kube import (
    KubeClusterClient,
    decode_node,
    decode_pdb,
    decode_pod,
)
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.cluster import NodeSpec, PDBSpec, PodSpec
from k8s_spot_rescheduler_tpu.utils.clock import Clock, RealClock
from k8s_spot_rescheduler_tpu.utils import logging as log

# The server closes an idle watch after this many seconds and we reconnect
# from the last seen resourceVersion; the socket timeout sits above it so a
# healthy-but-idle stream is never mistaken for a dead one.
WATCH_TIMEOUT_SECONDS = 300
RECONNECT_BACKOFF_INITIAL = 1.0
RECONNECT_BACKOFF_MAX = 30.0
# Slack added to the client-side socket timeout above the progress
# deadline: with timeoutSeconds capped AT the deadline, a healthy server
# always closes the stream first — only a wedged transport ever reaches
# the socket timeout, so the timeout firing IS the stall verdict.
WATCH_STALL_SLACK = 30.0


def _is_timeout(err: Exception) -> bool:
    """True for the socket-read timeout family a wedged-open stream
    produces (bare ``TimeoutError``/``socket.timeout`` during body
    reads, or URLError-wrapped when it fires at connect time)."""
    timeouts = (TimeoutError, socket.timeout)
    if isinstance(err, timeouts):
        return True
    return isinstance(err, urllib.error.URLError) and isinstance(
        getattr(err, "reason", None), timeouts
    )


class ResourceStore:
    """Thread-safe keyed store for one resource type, fed by a watcher.

    An optional listener (``subscribe``) observes every mutation under the
    store lock — the hook the columnar delta feed rides on. The listener
    must be cheap and non-blocking (it appends to a deque).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._items: Dict[str, object] = {}
        self.synced = threading.Event()
        self._listener = None  # callable(action, key, obj) under lock

    def subscribe(self, listener) -> List[object]:
        """Install the mutation listener and return the current items —
        atomically, so the subscriber misses no event and sees none twice."""
        with self._lock:
            self._listener = listener
            return list(self._items.values())

    def replace(self, items: Dict[str, object]) -> None:
        with self._lock:
            self._items = dict(items)
            if self._listener is not None:
                self._listener("replace", "", list(items.values()))
        self.synced.set()

    def upsert(self, key: str, obj: object, guard=None) -> bool:
        """``guard`` (checked under the store lock) lets a watcher make
        its apply atomic with a cancellation flag: a thread that passed
        its pre-check and then blocked on this lock while an audit
        replaced the store must not land its stale object afterwards."""
        with self._lock:
            if guard is not None and not guard():
                return False
            self._items[key] = obj
            if self._listener is not None:
                self._listener("upsert", key, obj)
            return True

    def delete(self, key: str, guard=None) -> bool:
        with self._lock:
            if guard is not None and not guard():
                return False
            old = self._items.pop(key, None)
            if old is not None and self._listener is not None:
                self._listener("delete", key, old)
            return True

    def snapshot(self) -> List[object]:
        with self._lock:
            return list(self._items.values())

    def snapshot_items(self) -> List[tuple]:
        """(key, obj) pairs — for callers that must upsert back under the
        SAME key (watch keys are apiserver UIDs, not ns/name)."""
        with self._lock:
            return list(self._items.items())

    def replace_if_same(self, key: str, old: object, new: object) -> bool:
        """Upsert ``new`` only if ``key`` still maps to ``old`` — the
        compare-and-swap for read-resolve-writeback callers racing the
        watcher thread (a concurrent MODIFIED/DELETED wins)."""
        with self._lock:
            if self._items.get(key) is not old:
                return False
            self._items[key] = new
            if self._listener is not None:
                self._listener("upsert", key, new)
            return True

    @property
    def lock(self) -> threading.Lock:
        """The store's mutation lock — for multi-store atomic freezes."""
        return self._lock

    def items_unlocked(self) -> List[object]:
        """Like snapshot() but the caller already holds .lock."""
        return list(self._items.values())


class _Expired(Exception):
    """resourceVersion too old — fall back to a fresh LIST."""


class Watcher(threading.Thread):
    """Background list-then-watch loop keeping one ResourceStore current.

    Liveness: every sign of progress — an applied event, a BOOKMARK, a
    clean server-side stream close, a successful (re-)LIST — stamps
    ``last_progress_wall`` from the injected clock. ``staleness()`` is
    the mirror-trust primitive the controller's freshness gate reads.
    With a ``progress_deadline`` set, the watch's server-side
    ``timeoutSeconds`` is capped at the deadline (a healthy-but-idle
    server then closes the stream within it, which counts as progress)
    and the client socket timeout sits ``WATCH_STALL_SLACK`` above it —
    so the socket timeout firing means the transport was open but
    wedged: the stream is killed, counted in ``watch_stalls_total``,
    and reconnected from the still-valid resourceVersion WITHOUT a
    re-LIST (nothing was missed; a wedge is not data loss).

    The protocol loop is a sequence of ``step()`` calls so a virtual-
    clock soak can drive it synchronously; ``run()`` just loops it on
    the daemon thread.
    """

    def __init__(
        self,
        client: KubeClusterClient,
        list_path: str,
        decode: Callable[[dict], object],
        key: Callable[[dict], str],
        store: ResourceStore,
        *,
        name: str = "watcher",
        clock: Optional[Clock] = None,
        progress_deadline: float = 0.0,
        wait_fn: Optional[Callable[[float], None]] = None,
    ) -> None:
        super().__init__(name=f"watch-{name}", daemon=True)
        self.client = client
        self.list_path = list_path
        self.decode = decode
        self.key = key
        self.store = store
        self.resource = name
        self.clock = clock or RealClock()
        self.progress_deadline = float(progress_deadline)
        # reconnect-backoff sleeps go through wait_fn when injected (the
        # synchronous soak passes the fake clock's sleep); the default
        # waits on the stop event so stop() returns promptly mid-backoff
        self._wait_fn = wait_fn
        # NOT named _stop: threading.Thread.join() internally calls a
        # private self._stop() method, which an Event attribute of the
        # same name would shadow (TypeError on join after exit)
        self._stopped = threading.Event()
        # observability for tests and debugging (mirrored into the
        # watch_* Prometheus series as they change)
        self.relist_count = 0
        self.event_count = 0
        self.stream_error_count = 0
        self.stall_count = 0
        # wall timestamp of the last proven progress; None until the
        # seeding LIST lands (staleness reads as infinite before then)
        self.last_progress_wall: Optional[float] = None
        # protocol-loop state (owned by step(); run() is just the loop)
        self._rv = ""
        self._need_list = True
        self._backoff = RECONNECT_BACKOFF_INITIAL
        # set by restart_from(): resume watching at this version without
        # a re-LIST (the anti-entropy audit already replaced the store)
        self._resume_rv: Optional[str] = None
        # invoked after every successful re-LIST (seed or 410 recovery);
        # the watching client uses it to re-arm scans that full store
        # replacement could invalidate (e.g. unresolved-PVC tracking)
        self.on_relist: Optional[Callable[[], None]] = None

    def stop(self) -> None:
        self._stopped.set()

    # --- liveness ---

    def note_progress(self) -> None:
        """Stamp proven liveness: an event landed, the server closed the
        stream cleanly, a (re-)LIST succeeded, or an anti-entropy audit
        just proved (or restored) mirror-equals-LIST."""
        self.last_progress_wall = self.clock.wall()

    def staleness(self, now_wall: Optional[float] = None) -> float:
        """Wall seconds since this watcher last proved progress;
        infinite before the seeding LIST."""
        if self.last_progress_wall is None:
            return float("inf")
        if now_wall is None:
            now_wall = self.clock.wall()
        return max(0.0, now_wall - self.last_progress_wall)

    def restart_from(self, rv: str) -> None:
        """Abandon the current stream (at its next event boundary) and
        resume watching from ``rv`` WITHOUT a re-LIST — the anti-entropy
        audit just replaced the store from a LIST at exactly that
        version, so the running stream (which provably missed or
        corrupted updates) must not keep feeding it, and a second LIST
        would be pure waste."""
        self._resume_rv = rv

    def _wait(self, seconds: float) -> None:
        if self._wait_fn is not None:
            self._wait_fn(seconds)
        else:
            self._stopped.wait(seconds)

    # --- protocol steps ---

    def _native_relist(self):
        """LIST via the native ingest engine when it applies: returns
        (items dict keyed by metadata.uid, resourceVersion) or None."""
        from k8s_spot_rescheduler_tpu.io import native_ingest

        if not getattr(self.client, "use_native_ingest", True):
            return None
        if not native_ingest.available():
            return None
        parse = {
            "/api/v1/pods": native_ingest.parse_pod_list,
            "/api/v1/nodes": native_ingest.parse_node_list,
        }.get(self.list_path)
        if parse is None:
            return None
        batch = parse(self.client._request_raw("GET", self.list_path))
        if batch is None:
            return None  # body didn't parse; Python path will retry
        items = {}
        for view in batch.views():
            key = view.meta_uid
            if not key:
                # a uid-less object can't be keyed consistently with the
                # raw-dict _meta_key later watch events will use — let the
                # Python re-list handle this (test/fake servers only; real
                # apiservers always set metadata.uid)
                return None
            items[key] = view
        return items, batch.resource_version

    def _fetch(self, *, native: bool = True):
        """One full LIST, decoded: (items dict, resourceVersion). The
        anti-entropy audit passes ``native=False`` so its items decode
        through the exact per-event Python path the mirror's contents
        came from (comparable field-by-field)."""
        if native:
            got = self._native_relist()
            if got is not None:
                return got
        obj = self.client._request("GET", self.list_path)
        items = {}
        for raw in obj.get("items", []) or []:
            items[self.key(raw)] = self.decode(raw)
        rv = (obj.get("metadata", {}) or {}).get("resourceVersion", "")
        return items, rv

    def _relist(self) -> str:
        items, rv = self._fetch()
        self.store.replace(items)
        self.relist_count += 1
        metrics.update_watch_relist(self.resource)
        self.note_progress()
        if self.on_relist is not None:
            self.on_relist()
        log.vlog(
            3, "watch %s: listed %d items at rv=%s",
            self.resource, len(items), rv,
        )
        return rv

    def _apply(self, event: dict, rv: str) -> str:
        etype = event.get("type", "")
        obj = event.get("object", {}) or {}
        if etype == "BOOKMARK":
            return (obj.get("metadata", {}) or {}).get("resourceVersion", rv)
        if etype == "ERROR":
            # k8s encodes watch failures as a Status object; 410 means the
            # resourceVersion fell out of etcd's window — re-list.
            code = int(obj.get("code", 0) or 0)
            reason = obj.get("reason", "")
            if code == 410 or reason == "Expired":
                raise _Expired(obj.get("message", "resourceVersion expired"))
            raise RuntimeError(f"watch ERROR event: {obj}")
        key = self.key(obj)
        # guarded apply, atomic with the restart flag UNDER the store
        # lock: if this thread passed the stream loop's pre-check and
        # then blocked on the lock while an audit heal replaced the
        # store (the audit sets _resume_rv BEFORE replacing), the stale
        # object must not land on top of the healed state
        guard = (
            lambda: self._resume_rv is None and not self._stopped.is_set()
        )
        if etype in ("ADDED", "MODIFIED"):
            applied = self.store.upsert(key, self.decode(obj), guard=guard)
        elif etype == "DELETED":
            applied = self.store.delete(key, guard=guard)
        else:
            applied = True
        if applied:
            self.event_count += 1
            metrics.update_watch_event(self.resource)
        return (obj.get("metadata", {}) or {}).get("resourceVersion", rv)

    def _watch(self, rv: str) -> str:
        sep = "&" if "?" in self.list_path else "?"
        # with a progress deadline the server-side timeout is capped AT
        # it, so a healthy idle stream is cleanly closed (= progress)
        # before the client-side socket timeout — which then only ever
        # fires on a genuinely wedged transport
        if self.progress_deadline > 0:
            server_timeout = min(
                WATCH_TIMEOUT_SECONDS, max(1, int(self.progress_deadline))
            )
            read_timeout = self.progress_deadline + WATCH_STALL_SLACK
        else:
            server_timeout = WATCH_TIMEOUT_SECONDS
            read_timeout = WATCH_TIMEOUT_SECONDS + WATCH_STALL_SLACK
        path = (
            f"{self.list_path}{sep}watch=1&allowWatchBookmarks=true"
            f"&timeoutSeconds={server_timeout}"
            + (f"&resourceVersion={rv}" if rv else "")
        )
        for event in self.client._stream(path, read_timeout):
            # the resume/stop check runs BEFORE the apply: after an
            # audit heal (restart_from), one more event from the
            # abandoned stream would otherwise land ON TOP of the
            # healed store — stale content the resumed stream (which
            # starts past it) would never redeliver
            if self._stopped.is_set() or self._resume_rv is not None:
                break
            self.note_progress()
            rv = self._apply(event, rv)
        return rv

    def step(self) -> None:
        """One protocol iteration: honor a pending audit restart,
        (re-)LIST if needed, then consume one watch stream to its end
        (server close, error, stall, or stop). ``run`` loops this on
        the watcher thread; the seeded soak drives it synchronously."""
        resume = self._resume_rv
        if resume is not None:
            self._resume_rv = None
            self._rv = resume
            self._need_list = False
        watching = False
        try:
            if self._need_list:
                self._rv = self._relist()
                self._need_list = False
            watching = True
            self._rv = self._watch(self._rv)
            # server closed the stream normally (timeoutSeconds) —
            # proven progress; reconnect from the last version without
            # re-listing
            self.note_progress()
            self._backoff = RECONNECT_BACKOFF_INITIAL
        except _Expired:
            # brief pause before the full re-LIST: if etcd's compaction
            # window is shorter than our LIST+watch turnaround, an
            # unthrottled loop here would hammer the apiserver with
            # back-to-back full LISTs
            log.vlog(2, "watch %s: resourceVersion expired, re-listing "
                        "in %.1fs", self.resource, self._backoff)
            self._need_list = True
            self._wait(self._backoff)
            self._backoff = min(self._backoff * 2, RECONNECT_BACKOFF_MAX)
        except Exception as err:  # noqa: BLE001 — any transport error
            if self._stopped.is_set():
                return
            if watching and self.progress_deadline > 0 and _is_timeout(err):
                # open-but-silent stream killed by the client-side
                # progress deadline: the resourceVersion is still valid
                # (a wedge loses no events), so reconnect immediately
                # without a re-LIST — the deadline itself throttles a
                # server that keeps stalling. ``watching`` scopes this
                # to the stream: a timing-out LIST is an ordinary
                # transport error and must keep its exponential backoff
                # (the branch below), never a tight relist loop
                self.stall_count += 1
                metrics.update_watch_stall(self.resource)
                # same event, third surface: the flight recorder keeps
                # the stall in the postmortem ring beside the counters
                # (fires on the watcher thread — between ticks — so no
                # tick trace ID to carry)
                from k8s_spot_rescheduler_tpu.loop import flight

                flight.note_event(
                    "watch-stall",
                    cause="stream open but silent past the %.0fs "
                          "progress deadline; reconnected from rv=%s"
                          % (self.progress_deadline, self._rv),
                    resource=self.resource,
                )
                log.error(
                    "watch %s: stream open but silent past the %.0fs "
                    "progress deadline; killing and reconnecting from "
                    "rv=%s", self.resource, self.progress_deadline,
                    self._rv,
                )
                self._backoff = RECONNECT_BACKOFF_INITIAL
                return
            self.stream_error_count += 1
            metrics.update_watch_stream_error(self.resource)
            log.vlog(
                2, "watch %s: stream error (%s), retrying in %.1fs",
                self.resource, err, self._backoff,
            )
            self._need_list = True  # conservative: reconcile after an error
            self._wait(self._backoff)
            self._backoff = min(self._backoff * 2, RECONNECT_BACKOFF_MAX)

    def run(self) -> None:
        while not self._stopped.is_set():
            self.step()


def _audit_norm(obj):
    """Comparable form of a stored/fetched object for the anti-entropy
    diff. Native lazy views materialize to their spec dataclasses (the
    two decode paths are lockstep by contract, so equal JSON compares
    equal), and ``pvc_resolvable`` is masked out on pods: it is a
    resolution-retry control flag, not cluster state — a terminally
    unresolvable claim flips it in the mirror only (via writeback), and
    flagging that as drift would heal-loop every audit."""
    import dataclasses

    if hasattr(obj, "to_pod_spec"):
        obj = obj.to_pod_spec()
    elif hasattr(obj, "to_node_spec"):
        obj = obj.to_node_spec()
    if isinstance(obj, PodSpec) and obj.pvc_resolvable:
        obj = dataclasses.replace(obj, pvc_resolvable=False)
    return obj


def _shared_batch(objs):
    """The native PodBatch behind a list of PodViews, if they all share
    one (a LIST seeds the store from a single batch)."""
    if not objs:
        return None
    batch = getattr(objs[0], "_b", None)
    if batch is None or not hasattr(batch, "tol_sets"):
        return None
    if all(getattr(o, "_b", None) is batch for o in objs) and len(objs) == (
        batch.count
    ):
        return batch
    return None


class ColumnarFeed:
    """Bridges the watch caches into a ``models/columnar.ColumnarStore``.

    Watcher threads enqueue deltas (under the store lock, via
    ``ResourceStore.subscribe``); the control-loop thread drains the queue
    once per tick (``sync``) and applies it to the columnar arrays — so
    the numpy state is only ever touched from one thread, and a tick sees
    a frozen point-in-time cluster, exactly like the object snapshot.

    A watcher re-list (410 Gone recovery) arrives as one ``replace`` delta
    and is reconciled by key diff: vanished objects are removed, everything
    present is upserted (same-node pod upserts keep their slot order).
    """

    def __init__(self, store, nodes: ResourceStore, pods: ResourceStore):
        import collections

        self.store = store
        # every mutation reaches the store through its mutators (watch
        # events decode fresh objects), so the version-keyed pack memo
        # is sound here: a zero-delta tick re-reads the previous pack
        store.pack_memo_enabled = True
        self._deltas = collections.deque()  # (kind, action, obj)
        # subscribe atomically: the returned seed lists are exactly the
        # state before any queued delta (no missed or doubled events)
        for obj in nodes.subscribe(
            lambda a, k, o: self._deltas.append(("node", a, o))
        ):
            self._apply("node", "upsert", obj)
        pod_seed = pods.subscribe(
            lambda a, k, o: self._deltas.append(("pod", a, o))
        )
        batch = _shared_batch(pod_seed)
        if batch is None or not store.bulk_add_pods(batch):
            for obj in pod_seed:
                self._apply("pod", "upsert", obj)

    def _apply(self, kind: str, action: str, obj) -> None:
        store = self.store
        if kind == "pod":
            if action == "upsert":
                store.add_pod(obj)
            elif action == "delete":
                store.remove_pod(obj.uid)
            else:  # replace (re-list after 410 Gone)
                batch = _shared_batch(obj)
                if batch is not None and store.bulk_add_pods(batch):
                    return  # empty store seeded in one vectorized pass
                store.reconcile_pods(obj)
        else:
            if action == "upsert":
                store.add_node(obj)
            elif action == "delete":
                store.remove_node(obj.name)
            else:  # replace
                store.reconcile_nodes(obj)

    def sync(self) -> int:
        """Drain queued deltas into the columnar store (tick thread only).
        Returns the number of deltas applied."""
        n = 0
        while self._deltas:
            kind, action, obj = self._deltas.popleft()
            self._apply(kind, action, obj)
            n += 1
        return n


class WatchingKubeClusterClient:
    """ClusterClient served from watch caches; writes pass through.

    Wraps a ``KubeClusterClient`` (which keeps doing the write path and
    provides the HTTP plumbing) with three watchers matching the
    reference's listers. ``list_unschedulable_pods`` — the first read of
    every housekeeping tick — freezes the live stores into a consistent
    per-tick snapshot.
    """

    def __init__(
        self,
        client: KubeClusterClient,
        *,
        clock: Optional[Clock] = None,
        progress_deadline: float = 0.0,
        wait_fn: Optional[Callable[[float], None]] = None,
    ) -> None:
        self.client = client
        self.clock = clock or RealClock()
        self.nodes = ResourceStore()
        self.pods = ResourceStore()
        self.pdbs = ResourceStore()
        # PVC/PV snapshots for volume-affinity resolution
        # (models/volumes.py): seeded before the pod watcher starts (a
        # running pod's binding pre-dates it) and refreshed per tick
        # while unresolved claims remain. Resolution failures leave pods
        # conservatively unplaceable. Held as ONE tuple so the watcher
        # thread's decode reads a consistent (pvcs, pvs) pair while the
        # tick thread reassigns it (advisor r3: two separate attribute
        # loads could pair a new PVC map with an old PV map).
        self._vol_snapshot: Tuple[Dict[str, object], Dict[str, object]] = (
            {}, {},
        )
        # re-scan the pod store for unresolved PVC pods only when
        # something could have produced one: the decode hook saw an
        # unresolved pod, or a re-LIST replaced the store wholesale
        # (the native bulk path bypasses the hook). Keeps the per-tick
        # _refresh_volumes a pure no-op for clusters without claims —
        # a 50k-pod python scan per tick would cost real time.
        self._vol_scan_needed = True
        self._watchers = [
            Watcher(client, "/api/v1/nodes", decode_node,
                    self._meta_key, self.nodes, name="nodes",
                    clock=self.clock, progress_deadline=progress_deadline,
                    wait_fn=wait_fn),
            Watcher(client, "/api/v1/pods", self._decode_pod_resolved,
                    self._meta_key, self.pods, name="pods",
                    clock=self.clock, progress_deadline=progress_deadline,
                    wait_fn=wait_fn),
            Watcher(client, "/apis/policy/v1/poddisruptionbudgets",
                    decode_pdb, self._meta_key, self.pdbs, name="pdbs",
                    clock=self.clock, progress_deadline=progress_deadline,
                    wait_fn=wait_fn),
        ]
        self._watchers[1].on_relist = self._arm_volume_scan
        # per-tick frozen view: node_name -> pods
        self._pods_by_node: Dict[str, List[PodSpec]] = {}
        self._tick_nodes: List[NodeSpec] = []
        self._tick_pdbs: List[PDBSpec] = []
        self._have_tick_view = False
        self._feed = None  # lazily attached ColumnarFeed

    # --- columnar fast path ---

    def columnar_store(
        self, resources, *, on_demand_label: str, spot_label: str
    ):
        """The incrementally-maintained columnar mirror, fed by the watch
        streams (SURVEY.md §5.8 "watch → numpy buffers"). Each call syncs
        queued watch deltas into the arrays — call it once per tick, from
        the control-loop thread."""
        from k8s_spot_rescheduler_tpu.models.columnar import ColumnarStore

        feed = self._feed
        if (
            feed is None
            or feed.store.resources != tuple(resources)
            or feed.store.on_demand_label != on_demand_label
            or feed.store.spot_label != spot_label
        ):
            store = ColumnarStore(
                resources,
                on_demand_label=on_demand_label,
                spot_label=spot_label,
            )
            feed = self._feed = ColumnarFeed(store, self.nodes, self.pods)
            # the seed read the live stores, which may be newer than the
            # tick's frozen object view — re-freeze so PDBs and the gate
            # view line up with the columnar state (one consistent instant)
            self._freeze()
        else:
            # columnar deltas are drained inside _freeze(), so the mirror
            # is exactly as old as the tick's frozen object/PDB view
            self._view()
        return feed.store

    @staticmethod
    def _meta_key(obj: dict) -> str:
        meta = obj.get("metadata", {}) or {}
        return meta.get("uid") or (
            meta.get("namespace", "") + "/" + meta.get("name", "")
        )

    # --- volume-affinity resolution ---

    def _decode_pod_resolved(self, obj: dict):
        from k8s_spot_rescheduler_tpu.models.volumes import (
            resolve_volume_affinity,
        )

        pod = decode_pod(obj)
        if pod.pvc_resolvable:
            pvcs, pvs = self._vol_snapshot  # one load: consistent pair
            pod = resolve_volume_affinity(pod, pvcs, pvs)
            if pod.pvc_resolvable:  # still unresolved: retry per tick
                self._vol_scan_needed = True
        return pod

    def _arm_volume_scan(self) -> None:
        self._vol_scan_needed = True

    def _refresh_volumes(self, force: bool = False) -> None:
        """Refetch the PVC/PV snapshots (cheap LISTs — these objects are
        few relative to pods) and re-resolve any still-unresolved PVC
        pods in the store. Skipped entirely while no pod carries
        resolvable claims; any failure keeps the old snapshot (pods stay
        conservatively unplaceable)."""
        import dataclasses

        from k8s_spot_rescheduler_tpu.models.cluster import PodSpec
        from k8s_spot_rescheduler_tpu.models.volumes import (
            resolve_volume_affinity,
            terminally_unresolvable,
        )

        if not self._vol_scan_needed and not force:
            return
        unresolved = [
            (key, p) for key, p in self.pods.snapshot_items()
            if getattr(p, "pvc_resolvable", False)
        ]
        if not unresolved:
            self._vol_scan_needed = False
            if not force:
                return
        try:
            pvcs, pvs = self.client.list_volume_snapshots()
            self._vol_snapshot = (pvcs, pvs)  # single atomic reassignment
        except Exception as err:  # noqa: BLE001, exception-discipline — stay conservative: unresolved volume pods remain unmodeled (the SAFE direction) and retry next tick; the kube retry layer counted the read failure
            log.error("PVC/PV list failed; volume pods stay unmodeled: %s", err)
            return
        for key, pod in unresolved:
            spec = pod if isinstance(pod, PodSpec) else pod.to_pod_spec()
            resolved = resolve_volume_affinity(spec, pvcs, pvs)
            if resolved is spec:
                if terminally_unresolvable(spec, pvcs, pvs):
                    # PV affinity is immutable: stop re-LISTing volumes
                    # for this pod every tick; it stays unmodeled
                    resolved = dataclasses.replace(spec, pvc_resolvable=False)
                else:
                    continue  # binding may still appear: retry next tick
            # writeback races the watcher thread: a concurrent MODIFIED/
            # DELETED event must win over this stale-read resolution
            self.pods.replace_if_same(key, pod, resolved)
        # retry only while a non-terminal unresolved pod remains
        self._vol_scan_needed = any(
            getattr(p, "pvc_resolvable", False)
            for p in self.pods.snapshot()
        )

    # --- lifecycle ---

    def start(
        self, timeout: Optional[float] = 30.0, *, background: bool = True
    ) -> None:
        """Start the watchers and block until every store has synced its
        initial LIST — the reference likewise waits for informer cache
        sync before the loop's first tick. ``background=False`` runs one
        synchronous protocol step per watcher instead of starting the
        threads — the deterministic mode the virtual-clock soak drives
        (it then calls ``Watcher.step()`` itself)."""
        # seed the PVC/PV maps BEFORE the pod watcher so JSON watch
        # events decode resolved from the first pod...
        self._refresh_volumes(force=True)
        if background:
            for w in self._watchers:
                w.start()
            for w in self._watchers:
                if not w.store.synced.wait(timeout):
                    raise TimeoutError(
                        f"watch cache for {w.resource} failed to sync "
                        f"within {timeout}s"
                    )
        else:
            for w in self._watchers:
                w.step()
                if not w.store.synced.is_set():
                    raise TimeoutError(
                        f"watch cache for {w.resource} failed to sync"
                    )
        # ...and resolve again AFTER the seed sync: the native bulk
        # relist path emits lazy views that bypass the decode hook
        self._refresh_volumes()

    def stop(self) -> None:
        for w in self._watchers:
            w.stop()

    # --- freshness and anti-entropy (docs/ROBUSTNESS.md) ---

    def mirror_staleness(self) -> float:
        """Wall seconds since the LEAST-live watch stream last proved
        progress — the controller's freshness gate refuses to plan from
        the mirror past ``mirror_staleness_budget``. Infinite until
        every store has seeded."""
        now = self.clock.wall()
        return max(w.staleness(now) for w in self._watchers)

    def direct_client(self):
        """The wrapped polling client — the freshness gate's bypass
        path. Its reads go straight to the apiserver (one LIST per
        view), never consulting the possibly-sick watch caches; writes
        were passing through to it anyway."""
        return self.client

    def resync_audit(self) -> Dict[str, int]:
        """Anti-entropy pass: one fresh LIST per watched resource,
        diffed field-by-field against the incremental mirror. Drift —
        a missing object, a phantom, or any field divergence — forces
        the store to be replaced from the LIST (one ``replace`` delta:
        the columnar feed reconciles and the planner full-repacks) and
        the watcher to resume from the LIST's resourceVersion; it is
        counted per object in ``watch_drift_total``. A clean audit
        proves mirror==LIST, which re-stamps watch liveness for free.
        Returns {resource: drifted object count}; raises if a LIST
        fails (the controller logs and retries next interval)."""
        drift: Dict[str, int] = {}
        for w in self._watchers:
            # Churn tolerance (threaded mode): the watcher keeps
            # applying events while the LIST is fetched and diffed, and
            # the mirror may legitimately run AHEAD of the LIST for
            # objects that changed mid-audit. An entry only counts as
            # drifted if the mirror's copy is IDENTICAL (by object)
            # before and after the fetch — untouched across the whole
            # audit window — yet still disagrees with the LIST. Every
            # mirror entry predates the LIST request, so an untouched
            # divergent entry cannot be explained by in-audit churn.
            # (An event still in flight when the LIST was issued can be
            # flagged; the heal then merely fast-forwards the store to
            # the LIST's newer state — converging, never corrupting.)
            pre = dict(w.store.snapshot_items())
            items, rv = w._fetch(native=False)
            current = dict(w.store.snapshot_items())
            n_field = n_presence = 0
            for k in set(items) | set(current):
                if current.get(k) is not pre.get(k):
                    continue  # touched mid-audit: churn, not drift
                a, b = items.get(k), current.get(k)
                if a is None or b is None:
                    # presence divergence (missing or phantom object):
                    # often an ADDED/DELETED event still in flight at
                    # the LIST instant — healed, but counted apart from
                    # the alarm-grade field drift below
                    n_presence += 1
                elif _audit_norm(a) != _audit_norm(b):
                    n_field += 1
            n = n_field + n_presence
            drift[w.resource] = n
            if n:
                log.error(
                    "anti-entropy audit: %s mirror diverged from a "
                    "fresh LIST (%d field-drifted, %d missing/phantom); "
                    "replacing the store (rv=%s)",
                    w.resource, n_field, n_presence, rv,
                )
                if n_field:
                    metrics.update_watch_drift(w.resource, n_field)
                if n_presence:
                    metrics.update_watch_presence_heal(
                        w.resource, n_presence
                    )
                # restart BEFORE replace: a watcher thread blocked on
                # the store lock mid-apply re-checks _resume_rv under
                # that same lock (the guarded apply), so no stale event
                # from the abandoned stream can land on the healed state
                w.restart_from(rv)
                w.store.replace(items)
                if w.on_relist is not None:
                    w.on_relist()
            # clean or healed, the mirror now provably equals a fresh
            # LIST: that is progress even if the stream is wedged
            w.note_progress()
        metrics.update_resync_audit()
        # the frozen per-tick view may predate a heal; re-freeze lazily
        if any(drift.values()):
            self._have_tick_view = False
        return drift

    # --- consistent per-tick view ---

    def refresh(self) -> None:
        """Drop the frozen view so the next read re-freezes from the live
        stores — called by the control loop before a mid-tick re-observe
        (multi-drain re-plan), mirroring KubeClusterClient.refresh().
        Also the per-tick hook where unresolved PVC pods retry against a
        fresh PVC/PV snapshot (no-op while none exist)."""
        self._refresh_volumes()
        self._have_tick_view = False

    def _freeze(self) -> None:
        # The columnar mirror freezes at the same instant as the object
        # view and the PDB list: one consistent per-tick cluster state.
        # All three store locks are held while the delta feed drains and
        # the object views are copied — watcher threads mutate (and
        # enqueue deltas) only under their store's lock, so nothing can
        # land between the mirror drain and the object snapshot.
        n_deltas = 0
        with self.nodes.lock, self.pods.lock, self.pdbs.lock:
            if self._feed is not None:
                n_deltas = self._feed.sync()
            by_node: Dict[str, List[PodSpec]] = {}
            for pod in self.pods.items_unlocked():
                by_node.setdefault(pod.node_name, []).append(pod)
            self._pods_by_node = by_node
            self._tick_nodes = list(self.nodes.items_unlocked())
            self._tick_pdbs = list(self.pdbs.items_unlocked())
        self._have_tick_view = True
        if self._feed is not None:
            # outside the store locks: prometheus takes its own
            metrics.update_observe_delta_events(n_deltas)

    def _view(self) -> None:
        if not self._have_tick_view:
            self._freeze()

    # --- read path (lister equivalents) ---

    def list_unschedulable_pods(self) -> List[PodSpec]:
        # first read of every tick: retry any unresolved PVC pods
        # against a fresh PVC/PV snapshot (no-op while none exist),
        # then refresh the frozen view
        self._refresh_volumes()
        self._freeze()
        return [
            p for p in self._pods_by_node.get("", [])
            if p.phase == "Pending"
        ]

    def list_ready_nodes(self) -> List[NodeSpec]:
        self._view()
        return [n for n in self._tick_nodes if n.ready]

    def list_unready_nodes(self) -> List[NodeSpec]:
        # presence-only view (NodeMap.unready; zone/spread counts)
        self._view()
        return [n for n in self._tick_nodes if not n.ready]

    def list_pods_on_node(self, node_name: str) -> List[PodSpec]:
        self._view()
        return list(self._pods_by_node.get(node_name, []))

    def list_pdbs(self) -> List[PDBSpec]:
        self._view()
        return list(self._tick_pdbs)

    def get_pod(self, namespace: str, name: str) -> Optional[PodSpec]:
        # actuation-path read (eviction verify poll, scaler/scaler.go:123):
        # must see live state, not the tick snapshot — a pod that just
        # terminated has to read as gone, so go straight to the apiserver.
        return self.client.get_pod(namespace, name)

    # --- write path + events: pass through ---

    def evict_pod(self, pod: PodSpec, grace_seconds: int) -> None:
        self.client.evict_pod(pod, grace_seconds)

    def add_taint(self, node_name: str, taint) -> None:
        self.client.add_taint(node_name, taint)

    def remove_taint(self, node_name: str, taint_key: str) -> None:
        self.client.remove_taint(node_name, taint_key)

    def event(self, *args, **kwargs) -> None:
        self.client.event(*args, **kwargs)
