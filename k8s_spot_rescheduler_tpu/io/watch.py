"""Watch-backed cluster caches — the reference's lister equivalents.

The reference never LISTs the whole cluster on its hot path: it builds
three watch-cache listers at startup (reference rescheduler.go:154-156,
``NewReadyNodeLister`` / ``NewPodDisruptionBudgetLister`` /
``NewUnschedulablePodLister``) and every per-tick read hits the local
cache that a background watch stream keeps current. ``KubeClusterClient``
(io/kube.py) approximates that with one full LIST per tick — correct, but
at north-star scale (50k pods) each tick re-transfers the entire pod set.

This module is the faithful equivalent: per-resource background watchers
following the standard Kubernetes list-then-watch protocol —

1. LIST to seed the store and learn ``metadata.resourceVersion``;
2. WATCH from that version with ``allowWatchBookmarks`` — apply
   ADDED/MODIFIED/DELETED incrementally, advance the version on BOOKMARK;
3. on 410 Gone (version expired from etcd) or any stream error, re-LIST
   and resume — the store is level-triggered, never wedged.

``WatchingKubeClusterClient`` serves the ``ClusterClient`` read path from
these stores. Each housekeeping tick gets one *consistent snapshot*: the
first read of a tick (``list_unschedulable_pods``, the loop's safety gate)
freezes the live stores into a per-tick view, so a tick never sees a pod
on two nodes because an event arrived mid-tick. Writes (evictions, taints,
events) pass through to the underlying client unchanged.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from k8s_spot_rescheduler_tpu.io.kube import (
    KubeClusterClient,
    decode_node,
    decode_pdb,
    decode_pod,
)
from k8s_spot_rescheduler_tpu.models.cluster import NodeSpec, PDBSpec, PodSpec
from k8s_spot_rescheduler_tpu.utils import logging as log

# The server closes an idle watch after this many seconds and we reconnect
# from the last seen resourceVersion; the socket timeout sits above it so a
# healthy-but-idle stream is never mistaken for a dead one.
WATCH_TIMEOUT_SECONDS = 300
RECONNECT_BACKOFF_INITIAL = 1.0
RECONNECT_BACKOFF_MAX = 30.0


class ResourceStore:
    """Thread-safe keyed store for one resource type, fed by a watcher.

    An optional listener (``subscribe``) observes every mutation under the
    store lock — the hook the columnar delta feed rides on. The listener
    must be cheap and non-blocking (it appends to a deque).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._items: Dict[str, object] = {}
        self.synced = threading.Event()
        self._listener = None  # callable(action, key, obj) under lock

    def subscribe(self, listener) -> List[object]:
        """Install the mutation listener and return the current items —
        atomically, so the subscriber misses no event and sees none twice."""
        with self._lock:
            self._listener = listener
            return list(self._items.values())

    def replace(self, items: Dict[str, object]) -> None:
        with self._lock:
            self._items = dict(items)
            if self._listener is not None:
                self._listener("replace", "", list(items.values()))
        self.synced.set()

    def upsert(self, key: str, obj: object) -> None:
        with self._lock:
            self._items[key] = obj
            if self._listener is not None:
                self._listener("upsert", key, obj)

    def delete(self, key: str) -> None:
        with self._lock:
            old = self._items.pop(key, None)
            if old is not None and self._listener is not None:
                self._listener("delete", key, old)

    def snapshot(self) -> List[object]:
        with self._lock:
            return list(self._items.values())

    def snapshot_items(self) -> List[tuple]:
        """(key, obj) pairs — for callers that must upsert back under the
        SAME key (watch keys are apiserver UIDs, not ns/name)."""
        with self._lock:
            return list(self._items.items())

    def replace_if_same(self, key: str, old: object, new: object) -> bool:
        """Upsert ``new`` only if ``key`` still maps to ``old`` — the
        compare-and-swap for read-resolve-writeback callers racing the
        watcher thread (a concurrent MODIFIED/DELETED wins)."""
        with self._lock:
            if self._items.get(key) is not old:
                return False
            self._items[key] = new
            if self._listener is not None:
                self._listener("upsert", key, new)
            return True

    @property
    def lock(self) -> threading.Lock:
        """The store's mutation lock — for multi-store atomic freezes."""
        return self._lock

    def items_unlocked(self) -> List[object]:
        """Like snapshot() but the caller already holds .lock."""
        return list(self._items.values())


class _Expired(Exception):
    """resourceVersion too old — fall back to a fresh LIST."""


class Watcher(threading.Thread):
    """Background list-then-watch loop keeping one ResourceStore current."""

    def __init__(
        self,
        client: KubeClusterClient,
        list_path: str,
        decode: Callable[[dict], object],
        key: Callable[[dict], str],
        store: ResourceStore,
        *,
        name: str = "watcher",
    ) -> None:
        super().__init__(name=f"watch-{name}", daemon=True)
        self.client = client
        self.list_path = list_path
        self.decode = decode
        self.key = key
        self.store = store
        self.resource = name
        self._stop = threading.Event()
        # observability for tests and debugging
        self.relist_count = 0
        self.event_count = 0
        # invoked after every successful re-LIST (seed or 410 recovery);
        # the watching client uses it to re-arm scans that full store
        # replacement could invalidate (e.g. unresolved-PVC tracking)
        self.on_relist: Optional[Callable[[], None]] = None

    def stop(self) -> None:
        self._stop.set()

    # --- protocol steps ---

    def _native_relist(self):
        """LIST via the native ingest engine when it applies: returns
        (items dict keyed by metadata.uid, resourceVersion) or None."""
        from k8s_spot_rescheduler_tpu.io import native_ingest

        if not getattr(self.client, "use_native_ingest", True):
            return None
        if not native_ingest.available():
            return None
        parse = {
            "/api/v1/pods": native_ingest.parse_pod_list,
            "/api/v1/nodes": native_ingest.parse_node_list,
        }.get(self.list_path)
        if parse is None:
            return None
        batch = parse(self.client._request_raw("GET", self.list_path))
        if batch is None:
            return None  # body didn't parse; Python path will retry
        items = {}
        for view in batch.views():
            key = view.meta_uid
            if not key:
                # a uid-less object can't be keyed consistently with the
                # raw-dict _meta_key later watch events will use — let the
                # Python re-list handle this (test/fake servers only; real
                # apiservers always set metadata.uid)
                return None
            items[key] = view
        return items, batch.resource_version

    def _relist(self) -> str:
        native = self._native_relist()
        if native is not None:
            items, rv = native
        else:
            obj = self.client._request("GET", self.list_path)
            items = {}
            for raw in obj.get("items", []) or []:
                items[self.key(raw)] = self.decode(raw)
            rv = (obj.get("metadata", {}) or {}).get("resourceVersion", "")
        self.store.replace(items)
        self.relist_count += 1
        if self.on_relist is not None:
            self.on_relist()
        log.vlog(
            3, "watch %s: listed %d items at rv=%s",
            self.resource, len(items), rv,
        )
        return rv

    def _apply(self, event: dict, rv: str) -> str:
        etype = event.get("type", "")
        obj = event.get("object", {}) or {}
        if etype == "BOOKMARK":
            return (obj.get("metadata", {}) or {}).get("resourceVersion", rv)
        if etype == "ERROR":
            # k8s encodes watch failures as a Status object; 410 means the
            # resourceVersion fell out of etcd's window — re-list.
            code = int(obj.get("code", 0) or 0)
            reason = obj.get("reason", "")
            if code == 410 or reason == "Expired":
                raise _Expired(obj.get("message", "resourceVersion expired"))
            raise RuntimeError(f"watch ERROR event: {obj}")
        key = self.key(obj)
        if etype in ("ADDED", "MODIFIED"):
            self.store.upsert(key, self.decode(obj))
        elif etype == "DELETED":
            self.store.delete(key)
        self.event_count += 1
        return (obj.get("metadata", {}) or {}).get("resourceVersion", rv)

    def _watch(self, rv: str) -> str:
        sep = "&" if "?" in self.list_path else "?"
        path = (
            f"{self.list_path}{sep}watch=1&allowWatchBookmarks=true"
            f"&timeoutSeconds={WATCH_TIMEOUT_SECONDS}"
            + (f"&resourceVersion={rv}" if rv else "")
        )
        for event in self.client._stream(path):
            rv = self._apply(event, rv)
            if self._stop.is_set():
                break
        return rv

    def run(self) -> None:
        backoff = RECONNECT_BACKOFF_INITIAL
        rv = ""
        need_list = True
        while not self._stop.is_set():
            try:
                if need_list:
                    rv = self._relist()
                    need_list = False
                rv = self._watch(rv)
                # server closed the stream normally (timeoutSeconds) —
                # reconnect from the last version without re-listing
                backoff = RECONNECT_BACKOFF_INITIAL
            except _Expired:
                # brief pause before the full re-LIST: if etcd's compaction
                # window is shorter than our LIST+watch turnaround, an
                # unthrottled loop here would hammer the apiserver with
                # back-to-back full LISTs
                log.vlog(2, "watch %s: resourceVersion expired, re-listing "
                            "in %.1fs", self.resource, backoff)
                need_list = True
                self._stop.wait(backoff)
                backoff = min(backoff * 2, RECONNECT_BACKOFF_MAX)
            except Exception as err:  # noqa: BLE001 — any transport error
                if self._stop.is_set():
                    return
                log.vlog(
                    2, "watch %s: stream error (%s), retrying in %.1fs",
                    self.resource, err, backoff,
                )
                need_list = True  # conservative: reconcile after an error
                self._stop.wait(backoff)
                backoff = min(backoff * 2, RECONNECT_BACKOFF_MAX)


def _shared_batch(objs):
    """The native PodBatch behind a list of PodViews, if they all share
    one (a LIST seeds the store from a single batch)."""
    if not objs:
        return None
    batch = getattr(objs[0], "_b", None)
    if batch is None or not hasattr(batch, "tol_sets"):
        return None
    if all(getattr(o, "_b", None) is batch for o in objs) and len(objs) == (
        batch.count
    ):
        return batch
    return None


class ColumnarFeed:
    """Bridges the watch caches into a ``models/columnar.ColumnarStore``.

    Watcher threads enqueue deltas (under the store lock, via
    ``ResourceStore.subscribe``); the control-loop thread drains the queue
    once per tick (``sync``) and applies it to the columnar arrays — so
    the numpy state is only ever touched from one thread, and a tick sees
    a frozen point-in-time cluster, exactly like the object snapshot.

    A watcher re-list (410 Gone recovery) arrives as one ``replace`` delta
    and is reconciled by key diff: vanished objects are removed, everything
    present is upserted (same-node pod upserts keep their slot order).
    """

    def __init__(self, store, nodes: ResourceStore, pods: ResourceStore):
        import collections

        self.store = store
        self._deltas = collections.deque()  # (kind, action, obj)
        # subscribe atomically: the returned seed lists are exactly the
        # state before any queued delta (no missed or doubled events)
        for obj in nodes.subscribe(
            lambda a, k, o: self._deltas.append(("node", a, o))
        ):
            self._apply("node", "upsert", obj)
        pod_seed = pods.subscribe(
            lambda a, k, o: self._deltas.append(("pod", a, o))
        )
        batch = _shared_batch(pod_seed)
        if batch is None or not store.bulk_add_pods(batch):
            for obj in pod_seed:
                self._apply("pod", "upsert", obj)

    def _apply(self, kind: str, action: str, obj) -> None:
        store = self.store
        if kind == "pod":
            if action == "upsert":
                store.add_pod(obj)
            elif action == "delete":
                store.remove_pod(obj.uid)
            else:  # replace (re-list after 410 Gone)
                batch = _shared_batch(obj)
                if batch is not None and store.bulk_add_pods(batch):
                    return  # empty store seeded in one vectorized pass
                store.reconcile_pods(obj)
        else:
            if action == "upsert":
                store.add_node(obj)
            elif action == "delete":
                store.remove_node(obj.name)
            else:  # replace
                store.reconcile_nodes(obj)

    def sync(self) -> int:
        """Drain queued deltas into the columnar store (tick thread only).
        Returns the number of deltas applied."""
        n = 0
        while self._deltas:
            kind, action, obj = self._deltas.popleft()
            self._apply(kind, action, obj)
            n += 1
        return n


class WatchingKubeClusterClient:
    """ClusterClient served from watch caches; writes pass through.

    Wraps a ``KubeClusterClient`` (which keeps doing the write path and
    provides the HTTP plumbing) with three watchers matching the
    reference's listers. ``list_unschedulable_pods`` — the first read of
    every housekeeping tick — freezes the live stores into a consistent
    per-tick snapshot.
    """

    def __init__(self, client: KubeClusterClient) -> None:
        self.client = client
        self.nodes = ResourceStore()
        self.pods = ResourceStore()
        self.pdbs = ResourceStore()
        # PVC/PV snapshots for volume-affinity resolution
        # (models/volumes.py): seeded before the pod watcher starts (a
        # running pod's binding pre-dates it) and refreshed per tick
        # while unresolved claims remain. Resolution failures leave pods
        # conservatively unplaceable. Held as ONE tuple so the watcher
        # thread's decode reads a consistent (pvcs, pvs) pair while the
        # tick thread reassigns it (advisor r3: two separate attribute
        # loads could pair a new PVC map with an old PV map).
        self._vol_snapshot: Tuple[Dict[str, object], Dict[str, object]] = (
            {}, {},
        )
        # re-scan the pod store for unresolved PVC pods only when
        # something could have produced one: the decode hook saw an
        # unresolved pod, or a re-LIST replaced the store wholesale
        # (the native bulk path bypasses the hook). Keeps the per-tick
        # _refresh_volumes a pure no-op for clusters without claims —
        # a 50k-pod python scan per tick would cost real time.
        self._vol_scan_needed = True
        self._watchers = [
            Watcher(client, "/api/v1/nodes", decode_node,
                    self._meta_key, self.nodes, name="nodes"),
            Watcher(client, "/api/v1/pods", self._decode_pod_resolved,
                    self._meta_key, self.pods, name="pods"),
            Watcher(client, "/apis/policy/v1/poddisruptionbudgets",
                    decode_pdb, self._meta_key, self.pdbs, name="pdbs"),
        ]
        self._watchers[1].on_relist = self._arm_volume_scan
        # per-tick frozen view: node_name -> pods
        self._pods_by_node: Dict[str, List[PodSpec]] = {}
        self._tick_nodes: List[NodeSpec] = []
        self._tick_pdbs: List[PDBSpec] = []
        self._have_tick_view = False
        self._feed = None  # lazily attached ColumnarFeed

    # --- columnar fast path ---

    def columnar_store(
        self, resources, *, on_demand_label: str, spot_label: str
    ):
        """The incrementally-maintained columnar mirror, fed by the watch
        streams (SURVEY.md §5.8 "watch → numpy buffers"). Each call syncs
        queued watch deltas into the arrays — call it once per tick, from
        the control-loop thread."""
        from k8s_spot_rescheduler_tpu.models.columnar import ColumnarStore

        feed = self._feed
        if (
            feed is None
            or feed.store.resources != tuple(resources)
            or feed.store.on_demand_label != on_demand_label
            or feed.store.spot_label != spot_label
        ):
            store = ColumnarStore(
                resources,
                on_demand_label=on_demand_label,
                spot_label=spot_label,
            )
            feed = self._feed = ColumnarFeed(store, self.nodes, self.pods)
            # the seed read the live stores, which may be newer than the
            # tick's frozen object view — re-freeze so PDBs and the gate
            # view line up with the columnar state (one consistent instant)
            self._freeze()
        else:
            # columnar deltas are drained inside _freeze(), so the mirror
            # is exactly as old as the tick's frozen object/PDB view
            self._view()
        return feed.store

    @staticmethod
    def _meta_key(obj: dict) -> str:
        meta = obj.get("metadata", {}) or {}
        return meta.get("uid") or (
            meta.get("namespace", "") + "/" + meta.get("name", "")
        )

    # --- volume-affinity resolution ---

    def _decode_pod_resolved(self, obj: dict):
        from k8s_spot_rescheduler_tpu.models.volumes import (
            resolve_volume_affinity,
        )

        pod = decode_pod(obj)
        if pod.pvc_resolvable:
            pvcs, pvs = self._vol_snapshot  # one load: consistent pair
            pod = resolve_volume_affinity(pod, pvcs, pvs)
            if pod.pvc_resolvable:  # still unresolved: retry per tick
                self._vol_scan_needed = True
        return pod

    def _arm_volume_scan(self) -> None:
        self._vol_scan_needed = True

    def _refresh_volumes(self, force: bool = False) -> None:
        """Refetch the PVC/PV snapshots (cheap LISTs — these objects are
        few relative to pods) and re-resolve any still-unresolved PVC
        pods in the store. Skipped entirely while no pod carries
        resolvable claims; any failure keeps the old snapshot (pods stay
        conservatively unplaceable)."""
        import dataclasses

        from k8s_spot_rescheduler_tpu.models.cluster import PodSpec
        from k8s_spot_rescheduler_tpu.models.volumes import (
            resolve_volume_affinity,
            terminally_unresolvable,
        )

        if not self._vol_scan_needed and not force:
            return
        unresolved = [
            (key, p) for key, p in self.pods.snapshot_items()
            if getattr(p, "pvc_resolvable", False)
        ]
        if not unresolved:
            self._vol_scan_needed = False
            if not force:
                return
        try:
            pvcs, pvs = self.client.list_volume_snapshots()
            self._vol_snapshot = (pvcs, pvs)  # single atomic reassignment
        except Exception as err:  # noqa: BLE001 — stay conservative
            log.error("PVC/PV list failed; volume pods stay unmodeled: %s", err)
            return
        for key, pod in unresolved:
            spec = pod if isinstance(pod, PodSpec) else pod.to_pod_spec()
            resolved = resolve_volume_affinity(spec, pvcs, pvs)
            if resolved is spec:
                if terminally_unresolvable(spec, pvcs, pvs):
                    # PV affinity is immutable: stop re-LISTing volumes
                    # for this pod every tick; it stays unmodeled
                    resolved = dataclasses.replace(spec, pvc_resolvable=False)
                else:
                    continue  # binding may still appear: retry next tick
            # writeback races the watcher thread: a concurrent MODIFIED/
            # DELETED event must win over this stale-read resolution
            self.pods.replace_if_same(key, pod, resolved)
        # retry only while a non-terminal unresolved pod remains
        self._vol_scan_needed = any(
            getattr(p, "pvc_resolvable", False)
            for p in self.pods.snapshot()
        )

    # --- lifecycle ---

    def start(self, timeout: Optional[float] = 30.0) -> None:
        """Start the watchers and block until every store has synced its
        initial LIST — the reference likewise waits for informer cache
        sync before the loop's first tick."""
        # seed the PVC/PV maps BEFORE the pod watcher so JSON watch
        # events decode resolved from the first pod...
        self._refresh_volumes(force=True)
        for w in self._watchers:
            w.start()
        for w in self._watchers:
            if not w.store.synced.wait(timeout):
                raise TimeoutError(
                    f"watch cache for {w.resource} failed to sync "
                    f"within {timeout}s"
                )
        # ...and resolve again AFTER the seed sync: the native bulk
        # relist path emits lazy views that bypass the decode hook
        self._refresh_volumes()

    def stop(self) -> None:
        for w in self._watchers:
            w.stop()

    # --- consistent per-tick view ---

    def refresh(self) -> None:
        """Drop the frozen view so the next read re-freezes from the live
        stores — called by the control loop before a mid-tick re-observe
        (multi-drain re-plan), mirroring KubeClusterClient.refresh().
        Also the per-tick hook where unresolved PVC pods retry against a
        fresh PVC/PV snapshot (no-op while none exist)."""
        self._refresh_volumes()
        self._have_tick_view = False

    def _freeze(self) -> None:
        # The columnar mirror freezes at the same instant as the object
        # view and the PDB list: one consistent per-tick cluster state.
        # All three store locks are held while the delta feed drains and
        # the object views are copied — watcher threads mutate (and
        # enqueue deltas) only under their store's lock, so nothing can
        # land between the mirror drain and the object snapshot.
        with self.nodes.lock, self.pods.lock, self.pdbs.lock:
            if self._feed is not None:
                self._feed.sync()
            by_node: Dict[str, List[PodSpec]] = {}
            for pod in self.pods.items_unlocked():
                by_node.setdefault(pod.node_name, []).append(pod)
            self._pods_by_node = by_node
            self._tick_nodes = list(self.nodes.items_unlocked())
            self._tick_pdbs = list(self.pdbs.items_unlocked())
        self._have_tick_view = True

    def _view(self) -> None:
        if not self._have_tick_view:
            self._freeze()

    # --- read path (lister equivalents) ---

    def list_unschedulable_pods(self) -> List[PodSpec]:
        # first read of every tick: retry any unresolved PVC pods
        # against a fresh PVC/PV snapshot (no-op while none exist),
        # then refresh the frozen view
        self._refresh_volumes()
        self._freeze()
        return [
            p for p in self._pods_by_node.get("", [])
            if p.phase == "Pending"
        ]

    def list_ready_nodes(self) -> List[NodeSpec]:
        self._view()
        return [n for n in self._tick_nodes if n.ready]

    def list_unready_nodes(self) -> List[NodeSpec]:
        # presence-only view (NodeMap.unready; zone/spread counts)
        self._view()
        return [n for n in self._tick_nodes if not n.ready]

    def list_pods_on_node(self, node_name: str) -> List[PodSpec]:
        self._view()
        return list(self._pods_by_node.get(node_name, []))

    def list_pdbs(self) -> List[PDBSpec]:
        self._view()
        return list(self._tick_pdbs)

    def get_pod(self, namespace: str, name: str) -> Optional[PodSpec]:
        # actuation-path read (eviction verify poll, scaler/scaler.go:123):
        # must see live state, not the tick snapshot — a pod that just
        # terminated has to read as gone, so go straight to the apiserver.
        return self.client.get_pod(namespace, name)

    # --- write path + events: pass through ---

    def evict_pod(self, pod: PodSpec, grace_seconds: int) -> None:
        self.client.evict_pod(pod, grace_seconds)

    def add_taint(self, node_name: str, taint) -> None:
        self.client.add_taint(node_name, taint)

    def remove_taint(self, node_name: str, taint_key: str) -> None:
        self.client.remove_taint(node_name, taint_key)

    def event(self, *args, **kwargs) -> None:
        self.client.event(*args, **kwargs)
