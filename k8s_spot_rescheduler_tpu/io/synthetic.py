"""Synthetic cluster generation — the benchmark configs of BASELINE.md.

Descendant of the reference tests' fixture builders
(``createTestPod``/``createTestNode``/``createFakeClient``, reference
nodes/nodes_test.go:324-449), scaled from the 3+3-node fixture up to the
north-star 5k-node/50k-pod clusters with Zipf pod sizes, taints,
anti-affinity groups, PDBs and spot-interruption replay
(BASELINE.json ``configs`` 1-5).

Pods are packed onto nodes up to a target utilization so that some
on-demand nodes are genuinely drainable and spot capacity is contended but
not exhausted — the regime the rescheduler operates in (README.md:136-149).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.models.cluster import (
    CPU,
    EPHEMERAL,
    MEMORY,
    PODS,
    NodeSpec,
    OwnerRef,
    PDBSpec,
    PodSpec,
    Taint,
    Toleration,
)
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock

ON_DEMAND_LABELS = {"kubernetes.io/role": "worker"}
SPOT_LABELS = {"kubernetes.io/role": "spot-worker"}

# machine shapes: (cpu millicores, memory bytes, max pods, ephemeral bytes)
SHAPES = [
    (4000, 16 * 1024**3, 110, 100 * 1024**3),
    (8000, 32 * 1024**3, 110, 200 * 1024**3),
    (16000, 64 * 1024**3, 250, 400 * 1024**3),
]

SPOT_TAINT = Taint("cloud.provider/spot", "true", "NoSchedule")
SPOT_TOLERATION = Toleration("cloud.provider/spot", "true", "Equal", "NoSchedule")


@dataclasses.dataclass(frozen=True)
class SyntheticSpec:
    """Knobs for one benchmark config."""

    name: str
    n_on_demand: int
    n_spot: int
    n_pods: int
    zipf_sizes: bool = False
    taints: bool = False  # spot taint + partial toleration coverage
    anti_affinity: bool = False
    pdbs: bool = False
    # hostname/zone labels on every node + hard topologySpreadConstraints
    # on a sparse subset of apps (the round-4 modeled predicate under
    # churn; constrained replay)
    spread: bool = False
    # mean utilization targets (fraction of allocatable CPU)
    on_demand_util: float = 0.45
    spot_util: float = 0.50
    # resource dimensions the solver should pack for this config
    # (BASELINE.json: config 2 = 2 resources, configs 3-4 = 4 resources)
    resources: Tuple[str, ...] = (CPU, MEMORY)


CONFIGS = {
    # 1: the reference's own test-fixture scale (rescheduler_test.go:40-151)
    1: SyntheticSpec("fixture-3x3", 3, 3, 20),
    # 2: first scale step — uniform sizes, cpu+mem
    2: SyntheticSpec("500n-5kp", 250, 250, 5_000),
    # 3: north star — Zipf sizes, taints/tolerations, 4 resources
    3: SyntheticSpec("5kn-50kp-taints", 2_500, 2_500, 50_000,
                     zipf_sizes=True, taints=True,
                     resources=(CPU, MEMORY, EPHEMERAL, PODS)),
    # 4: combinatorial predicates at scale
    4: SyntheticSpec("5kn-50kp-affinity-pdb", 2_500, 2_500, 50_000,
                     zipf_sizes=True, taints=True, anti_affinity=True,
                     pdbs=True, resources=(CPU, MEMORY, EPHEMERAL, PODS)),
    # 5: streaming replay base cluster (events generated separately)
    5: SyntheticSpec("replay-1k-events", 500, 500, 8_000, zipf_sizes=True),
}

# Config-5 churn with the full predicate surface loaded on (round 4):
# taints + partial tolerations, anti-affinity groups, widened round-5
# selector terms (operator-based spread selectors, NotIn'd anti-affinity
# terms, cross-namespace scopes), PDBs, and sparse hostname/zone hard
# spread constraints — the constrained replay row of
# docs/RESULTS.md (bench.py --config 5 --constrained).
REPLAY_CONSTRAINED = SyntheticSpec(
    "replay-constrained", 500, 500, 8_000,
    zipf_sizes=True, taints=True, anti_affinity=True, pdbs=True, spread=True,
)


def _pod_sizes(rng: np.random.Generator, n: int, zipf: bool) -> np.ndarray:
    """CPU requests in millicores. Zipf-ish skew: many small pods, a few
    huge ones, clipped to [50m, 4000m]."""
    if zipf:
        raw = (rng.zipf(2.2, n) * 50).clip(50, 4000)
    else:
        raw = rng.integers(50, 500, n)
    return raw.astype(np.int64)


def generate_cluster(
    spec: SyntheticSpec,
    seed: int = 0,
    clock: Optional[FakeClock] = None,
    **fake_kwargs,
) -> FakeCluster:
    rng = np.random.default_rng(seed)
    fc = FakeCluster(clock or FakeClock(), **fake_kwargs)

    def mk_nodes(count: int, labels: dict, prefix: str, tainted: bool) -> List[NodeSpec]:
        nodes = []
        for i in range(count):
            cpu, mem, cap, eph = SHAPES[rng.integers(0, len(SHAPES))]
            node_labels = dict(labels)
            if spec.spread:
                name = f"{prefix}-{i}"
                node_labels["kubernetes.io/hostname"] = name
                node_labels["topology.kubernetes.io/zone"] = f"z{i % 4}"
            node = NodeSpec(
                name=f"{prefix}-{i}",
                labels=node_labels,
                allocatable={CPU: cpu, MEMORY: mem, PODS: cap, EPHEMERAL: eph},
                taints=[SPOT_TAINT] if tainted else [],
            )
            nodes.append(node)
            fc.add_node(node)
        return nodes

    on_demand = mk_nodes(spec.n_on_demand, ON_DEMAND_LABELS, "od", False)
    # with taints enabled, 40% of spot nodes carry the spot taint
    spot = []
    for i, node in enumerate(mk_nodes(spec.n_spot, SPOT_LABELS, "spot", False)):
        if spec.taints and rng.random() < 0.4:
            node.taints.append(SPOT_TAINT)
        spot.append(node)

    sizes = _pod_sizes(rng, spec.n_pods, spec.zipf_sizes)
    # memory request correlated with cpu: ~2-6 MiB per millicore
    mem_per_cpu = rng.integers(2, 6, spec.n_pods).astype(np.int64)
    mems = sizes * mem_per_cpu * 1024**2
    # ephemeral-storage correlated with cpu: ~16-128 KiB per millicore,
    # so even a fully packed node stays well under its SHAPES[eph] budget
    ephs = sizes * rng.integers(16, 128, spec.n_pods).astype(np.int64) * 1024

    # Fill the emptiest-fitting node first (biggest pods placed first) via a
    # max-heap on remaining budget — O(P log N), scales to 50k pods.
    import heapq

    all_nodes = [(n, spec.on_demand_util) for n in on_demand] + [
        (n, spec.spot_util) for n in spot
    ]
    heap = [
        (-(n.allocatable[CPU] * u), 0, idx)
        for idx, (n, u) in enumerate(all_nodes)
    ]
    heapq.heapify(heap)

    n_apps = max(4, spec.n_pods // 100)
    for p in np.argsort(-sizes):
        cpu = int(sizes[p])
        app = int(rng.integers(0, n_apps))
        if not heap:
            break
        neg_room, cnt, best = heap[0]
        if -neg_room < cpu:
            continue  # even the roomiest node is full at target utilization
        heapq.heappop(heap)
        node = all_nodes[best][0]
        if cnt + 1 < node.allocatable[PODS] - 5:
            heapq.heappush(heap, (neg_room + cpu, cnt + 1, best))
        # role-key check, not dict equality — spread mode adds
        # hostname/zone labels to every node
        is_spot = (
            node.labels.get("kubernetes.io/role")
            == SPOT_LABELS["kubernetes.io/role"]
        )
        tolerations = []
        if spec.taints and (is_spot or rng.random() < 0.7):
            # pods already on tainted spot nodes must tolerate; 70% of
            # on-demand pods are spot-tolerant (the movable majority)
            tolerations = [SPOT_TOLERATION]
        # sparse hard spread: every 13th app's pods carry the common
        # hostname+zone constraint pair over their own app label (the
        # round-4 modeled predicate; loose skews so drains stay
        # possible); every 26th uses the round-5 WIDENED selector form
        # (In over the app pair + a canary DoesNotExist) so churn
        # exercises operator-based spread counting too
        ns = f"ns-{app % 16}"
        spread_constraints = ()
        if spec.spread and app % 13 == 0:
            if app % 26 == 0:
                sel = (
                    ("app", "In", (f"app-{app}", f"app-{app}-canary")),
                    ("canary", "DoesNotExist", ()),
                )
            else:
                sel = (("app", f"app-{app}"),)
            spread_constraints = (
                ("kubernetes.io/hostname", 3, sel),
                ("topology.kubernetes.io/zone", 4, sel),
            )
        # sparse round-5 widened anti-affinity terms (on top of the
        # group-based 10%): every 17th app's pods refuse co-location
        # with SAME-APP pods via a NotIn-excluded sibling selector;
        # every 19th carries a CROSS-NAMESPACE term against the
        # neighboring namespace's copy of the app label. Loose by
        # construction (each app is a small fraction of any node) so
        # drains stay possible while the operators and ns scopes churn.
        anti_terms = ()
        if spec.anti_affinity and app % 17 == 0:
            anti_terms += (
                ((ns,), (
                    ("app", "In", (f"app-{app}",)),
                    ("decoy", "NotIn", ("1",)),
                )),
            )
        if spec.anti_affinity and app % 19 == 0:
            other_ns = f"ns-{(app + 1) % 16}"
            anti_terms += (
                (tuple(sorted({ns, other_ns})),
                 (("app", "In", (f"app-{app}",)),)),
            )
        pod = PodSpec(
            name=f"pod-{p}",
            namespace=ns,
            node_name=node.name,
            requests={CPU: cpu, MEMORY: int(mems[p]), EPHEMERAL: int(ephs[p])},
            labels={"app": f"app-{app}"},
            owner_refs=[OwnerRef("ReplicaSet", f"app-{app}-rs")],
            tolerations=tolerations,
            anti_affinity_group=(
                f"aff-{app}" if spec.anti_affinity and rng.random() < 0.1 else ""
            ),
            anti_affinity_match=anti_terms,
            spread_constraints=spread_constraints,
        )
        fc.add_pod(pod)

    if spec.pdbs:
        for a in range(0, n_apps, 3):  # every third app gets a PDB
            fc.pdbs.append(
                PDBSpec(
                    name=f"pdb-app-{a}",
                    namespace=f"ns-{a % 16}",
                    match_labels={"app": f"app-{a}"},
                    disruptions_allowed=int(rng.integers(1, 10)),
                )
            )
    return fc


@dataclasses.dataclass(frozen=True)
class ContendedSpec:
    """Adversarial quality config: node pools at high spot utilization
    where greedy packing demonstrably loses drains.

    The cluster is G independent pools (apps pinned to their pool's spot
    nodes via ``spec.nodeSelector`` — the standard multi-node-pool k8s
    pattern). Pool kinds, drawn per seed:

    - **easy** — ample slack; any solver proves the drain.
    - **swap** — the regime where one-pass greedy fails: the pool's
      untainted spot capacity is scarce and exactly fits the candidate's
      *intolerant* pod, but a *tolerant* pod is slightly bigger and sorts
      first, so first-fit (probe order: most-requested-first, reference
      rescheduler.go:336-344) and best-fit (tightest slack) both burn the
      untainted node on the tolerant pod and strand the intolerant one.
      Relocating the tolerant pod to the pool's looser *tainted* node —
      one eject-and-reinsert move (solver/repair.py) — unlocks the drain
      the ILP oracle finds.
    - **blocked** — the candidate's pod exceeds every pool node's slack;
      no solver (nor the oracle) drains it.

    Spot nodes in swap pools sit at ≥0.85 utilization; sizes jitter per
    seed so no solver can pattern-match the construction.
    """

    name: str
    n_groups: int = 12
    swap_frac: float = 0.5
    easy_frac: float = 0.35  # remainder of groups is blocked
    node_cpu: int = 4000
    resources: Tuple[str, ...] = (CPU, MEMORY)


@dataclasses.dataclass(frozen=True)
class AffinitySpec:
    """Round-4 adversarial pools: greedy loses *because of* required
    anti-affinity, and (optionally) a two-pod interlock that defeats
    depth-1 eject-reinsert — the published repair boundary.

    Pool kinds, drawn per seed:

    - **aswap** — the anti-affinity swap: two pods of one self-selecting
      group (labels ``app=app-g`` + required hostname anti-affinity
      matching that label — the k8s spread-via-anti-affinity pattern) on
      the candidate. The bigger one (T, spot-taint-tolerant) sorts
      first and greedy burns the pool's only untainted spot node on it;
      the smaller one (I, intolerant) then has nowhere: the tainted
      node refuses it and the untainted one now hosts its group-mate.
      Ejecting T to the tainted node — an AFFINITY-driven relocation,
      impossible under monotone affinity accumulation — frees the node
      for I. The affinity-aware ILP drains the pool; so does repair
      with exact ejection (solver/repair.py round 4).
    - **interlock** — the depth-1 boundary, CLOSED in round 4 by the
      depth-2 chain: the candidate holds A, B, C (sizes a > b > c).
      Greedy lands A on u1 (exactly a slack) and B on u2 (taint only
      A/B tolerate; b+ε slack, ε ≥ a-b); C fits only u1 (z's taint only
      B tolerates). The only unlocker is A, and A can re-place only on
      u2 — which needs B ejected first: the chained move
      (C→u1, A→u2, B→z) that depth-1 eject-reinsert cannot express and
      the round-4 depth-2 chain executes. Now part of the headline
      quality metric (shipped 1.000).
    - **chain3** — the NEW published boundary: a three-link chain
      (c→u1, m1→u2, m2→u3, m3→z) with per-level taints so each mover
      statically fits only its current and next node. The only unlocker
      (m1) can re-place only on u2, whose occupant m2 can re-place only
      on u3 — TWO chained ejections deep, beyond the depth-2 search.
      The ILP (simultaneous) drains it; shipped < 1.000 by
      construction.
    - **easy** — ample slack; any solver proves the drain.
    """

    name: str
    n_groups: int = 12
    aswap_frac: float = 0.5
    interlock_frac: float = 0.0
    chain3_frac: float = 0.0  # remainder of groups is easy
    node_cpu: int = 4000
    resources: Tuple[str, ...] = (CPU, MEMORY)


@dataclasses.dataclass(frozen=True)
class SpreadQualitySpec:
    """Round-5 adversarial pools: greedy loses a drain *because of* a
    hard topologySpreadConstraint, and repair recovers it.

    Per pool ``g`` (own namespace, pool-selector isolated): zone
    ``za-g`` holds spot-a with two selector-matched residents; zone
    ``zb-g`` holds spot-b with heavy NON-matching residents (so probe
    order ranks spot-b first). The candidate carries a big plain filler
    and a smaller zone-spread CARRIER (maxSkew 2, self-matching): the
    skew math refuses ``za-g`` (2 matched there, 0 in ``zb-g``), so the
    carrier fits ONLY spot-b — but greedy places the filler first, and
    both first-fit and best-fit (slack tie -> probe order) burn spot-b
    on it. The repair phase ejects the filler to spot-a and seats the
    carrier — a SPREAD-driven relocation. The ILP (which reads the same
    static SpreadBit words in the packed masks) proves one drain per
    pool; pure greedy proves zero. Static verdicts are EXACT here: one
    carrier per spread identity, nothing else matching its selector
    moves (the bench/quality.py exactness scope)."""

    name: str
    n_groups: int = 12
    resources: Tuple[str, ...] = (CPU, MEMORY)


QUALITY_CONFIGS = {
    # the round-1/2 balanced regime (greedy ties the oracle here — kept as
    # the regression guard that quality never drops below 1.0 on it)
    "balanced": SyntheticSpec("quality-40n-300p", 20, 20, 300),
    # contention: high-utilization pools, taints, selector-pinned apps
    "contended": ContendedSpec("quality-contended-12g"),
    # contention + Zipf-skewed background load on the easy pools
    "contended-zipf": ContendedSpec("quality-contended-zipf-16g", n_groups=16,
                                    swap_frac=0.4, easy_frac=0.45),
    # anti-affinity contention: drains only an affinity-driven
    # relocation recovers (VERDICT r3 #3)
    "affinity": AffinitySpec("quality-affinity-12g"),
    # two-pod interlocks: depth-1's old boundary, closed by the round-4
    # depth-2 chain — now a headline row
    "interlock": AffinitySpec("quality-interlock-8g", n_groups=8,
                              aswap_frac=0.0, interlock_frac=0.25),
    # hard topologySpread contention: drains only a spread-driven
    # relocation recovers (VERDICT r4 #3)
    "spread": SpreadQualitySpec("quality-spread-12g"),
}

# Published-boundary configs: NOT part of the headline worst-ratio metric
# (the boundary is a documented limitation, not a regression) — run via
# bench.py --quality-boundary and pinned by tests/test_quality_adversarial.
BOUNDARY_CONFIGS = {
    # three-link chains need TWO chained ejections; the depth-2 search
    # cannot express them — shipped < 1.000 BY CONSTRUCTION
    # (docs/RESULTS.md)
    "chain3": AffinitySpec("quality-chain3-8g", n_groups=8,
                           aswap_frac=0.0, chain3_frac=0.25),
}


def _mem_for(cpu: int) -> int:
    return int(cpu) * 2 * 1024**2  # 2 MiB per millicore: mem never binds


def generate_contended_cluster(
    spec: ContendedSpec, seed: int = 0, **fake_kwargs
) -> FakeCluster:
    rng = np.random.default_rng(seed)
    fc = FakeCluster(FakeClock(), **fake_kwargs)
    mem = 16 * 1024**3
    zipfish = "zipf" in spec.name

    def add_node(name, labels, taints=()):
        node = NodeSpec(
            name=name,
            labels=dict(labels),
            allocatable={CPU: spec.node_cpu, MEMORY: mem, PODS: 110,
                         EPHEMERAL: 100 * 1024**3},
            taints=list(taints),
        )
        fc.add_node(node)
        return node

    def add_pod(name, node, cpu, *, app, tolerations=(), selector=None):
        fc.add_pod(PodSpec(
            name=name,
            namespace=f"ns-{app % 16}",
            node_name=node,
            requests={CPU: int(cpu), MEMORY: _mem_for(cpu),
                      EPHEMERAL: int(cpu) * 64 * 1024},
            labels={"app": f"app-{app}"},
            owner_refs=[OwnerRef("ReplicaSet", f"app-{app}-rs")],
            tolerations=list(tolerations),
            node_selector=dict(selector or {}),
        ))

    kinds = (["swap"] * round(spec.n_groups * spec.swap_frac)
             + ["easy"] * round(spec.n_groups * spec.easy_frac))
    kinds += ["blocked"] * (spec.n_groups - len(kinds))
    rng.shuffle(kinds)

    for g, kind in enumerate(kinds):
        pool = {"pool": f"g{g}"}
        spot_labels = {**SPOT_LABELS, **pool}
        add_node(f"od-{g}", ON_DEMAND_LABELS)
        if kind == "swap":
            # untainted node: slack exactly one intolerant-pod-sized hole,
            # >=0.85 utilized; tainted node: loose enough to take the
            # tolerant pod after the repair move
            slack_u = int(rng.integers(540, 600))
            t_cpu = slack_u - int(rng.integers(5, 25))
            i_cpu = t_cpu - int(rng.integers(5, 15))
            slack_z = t_cpu + int(rng.integers(60, 140))
            add_node(f"spot-u-{g}", spot_labels)
            add_node(f"spot-z-{g}", spot_labels, [SPOT_TAINT])
            add_pod(f"res-u-{g}", f"spot-u-{g}", spec.node_cpu - slack_u,
                    app=g)
            add_pod(f"res-z-{g}", f"spot-z-{g}", spec.node_cpu - slack_z,
                    app=g, tolerations=[SPOT_TOLERATION])
            add_pod(f"tol-{g}", f"od-{g}", t_cpu, app=g,
                    tolerations=[SPOT_TOLERATION], selector=pool)
            add_pod(f"intol-{g}", f"od-{g}", i_cpu, app=g, selector=pool)
        elif kind == "easy":
            # two small pods, one spot node with comfortable slack
            if zipfish:
                sizes = (rng.zipf(2.2, 2) * 60).clip(60, 700).astype(int)
            else:
                sizes = rng.integers(150, 320, 2)
            slack = int(sizes.sum() + rng.integers(120, 260))
            add_node(f"spot-u-{g}", spot_labels)
            add_pod(f"res-u-{g}", f"spot-u-{g}", spec.node_cpu - slack,
                    app=g)
            for j, cpu in enumerate(sizes):
                add_pod(f"app-{g}-{j}", f"od-{g}", int(cpu), app=g,
                        selector=pool)
        else:  # blocked: pod larger than any slack in its pool
            slack = int(rng.integers(300, 480))
            add_node(f"spot-u-{g}", spot_labels)
            add_pod(f"res-u-{g}", f"spot-u-{g}", spec.node_cpu - slack,
                    app=g)
            add_pod(f"big-{g}", f"od-{g}", slack + int(rng.integers(300, 700)),
                    app=g, selector=pool)
    return fc


U2_TAINT = Taint("quality.test/reserved-u2", "1", "NoSchedule")
U2_TOLERATION = Toleration("quality.test/reserved-u2", "1", "Equal",
                           "NoSchedule")
U3_TAINT = Taint("quality.test/reserved-u3", "1", "NoSchedule")
U3_TOLERATION = Toleration("quality.test/reserved-u3", "1", "Equal",
                           "NoSchedule")


def generate_affinity_cluster(
    spec: AffinitySpec, seed: int = 0, **fake_kwargs
) -> FakeCluster:
    """See ``AffinitySpec`` — aswap / interlock / easy pools."""
    rng = np.random.default_rng(seed)
    fc = FakeCluster(FakeClock(), **fake_kwargs)
    mem = 16 * 1024**3

    def add_node(name, labels, taints=()):
        fc.add_node(NodeSpec(
            name=name,
            labels=dict(labels),
            allocatable={CPU: spec.node_cpu, MEMORY: mem, PODS: 110,
                         EPHEMERAL: 100 * 1024**3},
            taints=list(taints),
        ))

    def add_pod(name, node, cpu, *, app, labels=None, tolerations=(),
                selector=None, anti_match=None):
        fc.add_pod(PodSpec(
            name=name,
            namespace=f"ns-{app % 16}",
            node_name=node,
            requests={CPU: int(cpu), MEMORY: _mem_for(cpu),
                      EPHEMERAL: int(cpu) * 64 * 1024},
            labels=dict(labels if labels is not None else
                        {"app": f"app-{app}"}),
            owner_refs=[OwnerRef("ReplicaSet", f"app-{app}-rs")],
            tolerations=list(tolerations),
            node_selector=dict(selector or {}),
            anti_affinity_match=dict(anti_match or {}),
        ))

    kinds = (["aswap"] * round(spec.n_groups * spec.aswap_frac)
             + ["interlock"] * round(spec.n_groups * spec.interlock_frac)
             + ["chain3"] * round(spec.n_groups * spec.chain3_frac))
    kinds += ["easy"] * (spec.n_groups - len(kinds))
    rng.shuffle(kinds)

    for g, kind in enumerate(kinds):
        pool = {"pool": f"g{g}"}
        spot_labels = {**SPOT_LABELS, **pool}
        add_node(f"od-{g}", ON_DEMAND_LABELS)
        group_sel = {"app": f"app-{g}"}
        if kind == "aswap":
            # untainted node (plain resident) fits T-or-I one at a time;
            # tainted node is loose enough for T after the repair move
            slack_u = int(rng.integers(540, 600))
            t_cpu = slack_u - int(rng.integers(5, 25))
            i_cpu = t_cpu - int(rng.integers(5, 15))
            slack_z = t_cpu + int(rng.integers(60, 140))
            add_node(f"spot-u-{g}", spot_labels)
            add_node(f"spot-z-{g}", spot_labels, [SPOT_TAINT])
            add_pod(f"res-u-{g}", f"spot-u-{g}", spec.node_cpu - slack_u,
                    app=g, labels={"bg": f"bg-{g}"})
            add_pod(f"res-z-{g}", f"spot-z-{g}", spec.node_cpu - slack_z,
                    app=g, labels={"bg": f"bg-{g}"},
                    tolerations=[SPOT_TOLERATION])
            add_pod(f"tol-{g}", f"od-{g}", t_cpu, app=g,
                    tolerations=[SPOT_TOLERATION], selector=pool,
                    anti_match=group_sel)
            add_pod(f"intol-{g}", f"od-{g}", i_cpu, app=g,
                    selector=pool, anti_match=group_sel)
        elif kind == "interlock":
            b = int(rng.integers(300, 400))
            delta = int(rng.integers(5, 20))
            a = b + delta
            eps = delta + int(rng.integers(5, 20))
            zeta = eps + int(rng.integers(5, 20))
            c = int(rng.integers(150, min(250, b - 10)))
            add_node(f"spot-u1-{g}", spot_labels)
            add_node(f"spot-u2-{g}", spot_labels, [U2_TAINT])
            add_node(f"spot-z-{g}", spot_labels, [SPOT_TAINT])
            slack_u1 = a + int(rng.integers(0, 5))
            add_pod(f"res-u1-{g}", f"spot-u1-{g}",
                    spec.node_cpu - slack_u1, app=g,
                    labels={"bg": f"bg-{g}"})
            add_pod(f"res-u2-{g}", f"spot-u2-{g}",
                    spec.node_cpu - (b + eps), app=g,
                    labels={"bg": f"bg-{g}"}, tolerations=[U2_TOLERATION])
            add_pod(f"res-z-{g}", f"spot-z-{g}",
                    spec.node_cpu - (b + zeta), app=g,
                    labels={"bg": f"bg-{g}"}, tolerations=[SPOT_TOLERATION])
            add_pod(f"ilk-a-{g}", f"od-{g}", a, app=g, selector=pool,
                    tolerations=[U2_TOLERATION])
            add_pod(f"ilk-b-{g}", f"od-{g}", b, app=g, selector=pool,
                    tolerations=[U2_TOLERATION, SPOT_TOLERATION])
            add_pod(f"ilk-c-{g}", f"od-{g}", c, app=g, selector=pool)
        elif kind == "chain3":
            # three-link chain: c->u1, m1->u2 (eject m2), m2->u3 (eject
            # m3), m3->z. Per-level taints pin each mover to its current
            # and next node; slack ordering pins greedy's placements
            # (u1 fullest, then u2, u3, z). See AffinitySpec.
            m3 = int(rng.integers(280, 340))
            d3 = int(rng.integers(15, 25))
            m2 = m3 + d3
            d2 = int(rng.integers(15, 25))
            m1 = m2 + d2
            e2 = d2 + int(rng.integers(3, 10))
            e3 = d3 + e2 + int(rng.integers(3, 10))
            c = int(rng.integers(150, 250))
            slack_u1 = m1 + int(rng.integers(0, 5))
            slack_z = m3 + e3 + int(rng.integers(10, 60))
            add_node(f"spot-u1-{g}", spot_labels)
            add_node(f"spot-u2-{g}", spot_labels, [U2_TAINT])
            add_node(f"spot-u3-{g}", spot_labels, [U3_TAINT])
            add_node(f"spot-z-{g}", spot_labels, [SPOT_TAINT])
            add_pod(f"res-u1-{g}", f"spot-u1-{g}",
                    spec.node_cpu - slack_u1, app=g,
                    labels={"bg": f"bg-{g}"})
            add_pod(f"res-u2-{g}", f"spot-u2-{g}",
                    spec.node_cpu - (m2 + e2), app=g,
                    labels={"bg": f"bg-{g}"}, tolerations=[U2_TOLERATION])
            add_pod(f"res-u3-{g}", f"spot-u3-{g}",
                    spec.node_cpu - (m3 + e3), app=g,
                    labels={"bg": f"bg-{g}"}, tolerations=[U3_TOLERATION])
            add_pod(f"res-z-{g}", f"spot-z-{g}",
                    spec.node_cpu - slack_z, app=g,
                    labels={"bg": f"bg-{g}"}, tolerations=[SPOT_TOLERATION])
            add_pod(f"ch-m1-{g}", f"od-{g}", m1, app=g, selector=pool,
                    tolerations=[U2_TOLERATION])
            add_pod(f"ch-m2-{g}", f"od-{g}", m2, app=g, selector=pool,
                    tolerations=[U2_TOLERATION, U3_TOLERATION])
            add_pod(f"ch-m3-{g}", f"od-{g}", m3, app=g, selector=pool,
                    tolerations=[U3_TOLERATION, SPOT_TOLERATION])
            add_pod(f"ch-c-{g}", f"od-{g}", c, app=g, selector=pool)
        else:  # easy
            sizes = rng.integers(150, 320, 2)
            slack = int(sizes.sum() + rng.integers(120, 260))
            add_node(f"spot-u-{g}", spot_labels)
            add_pod(f"res-u-{g}", f"spot-u-{g}", spec.node_cpu - slack,
                    app=g, labels={"bg": f"bg-{g}"})
            for j, cpu in enumerate(sizes):
                add_pod(f"app-{g}-{j}", f"od-{g}", int(cpu), app=g,
                        selector=pool)
    return fc


from k8s_spot_rescheduler_tpu.predicates.masks import ZONE_LABEL


def generate_spread_quality_cluster(
    spec: SpreadQualitySpec, seed: int = 0, **fake_kwargs
) -> FakeCluster:
    """See ``SpreadQualitySpec`` — one spread-contended pool per group."""
    rng = np.random.default_rng(seed)
    fc = FakeCluster(FakeClock(), **fake_kwargs)
    mem = 16 * 1024**3

    def add_node(name, labels, cpu):
        fc.add_node(NodeSpec(
            name=name,
            labels=dict(labels),
            allocatable={CPU: int(cpu), MEMORY: mem, PODS: 110,
                         EPHEMERAL: 100 * 1024**3},
        ))

    for g in range(spec.n_groups):
        ns = f"ns-{g}"
        pool = {"pool": f"g{g}"}
        carrier_cpu = int(rng.integers(450, 550))
        filler_cpu = carrier_cpu + int(rng.integers(50, 150))
        matched_cpu = int(rng.integers(40, 60))
        heavy_total = int(rng.integers(850, 950))
        add_node(f"od-{g}", ON_DEMAND_LABELS, 2000)
        # spot-a (zone za-g): exactly filler-sized slack after its two
        # matched residents; LOW requested -> probed second
        add_node(
            f"spot-a-{g}",
            {**SPOT_LABELS, **pool, ZONE_LABEL: f"za-{g}"},
            filler_cpu + 2 * matched_cpu,
        )
        # spot-b (zone zb-g): filler-sized slack after heavy plain
        # residents; HIGH requested -> probed first, so greedy burns it
        add_node(
            f"spot-b-{g}",
            {**SPOT_LABELS, **pool, ZONE_LABEL: f"zb-{g}"},
            filler_cpu + heavy_total,
        )

        def add_pod(name, node, cpu, labels, spread=()):
            fc.add_pod(PodSpec(
                name=name,
                namespace=ns,
                node_name=node,
                requests={CPU: int(cpu), MEMORY: _mem_for(cpu)},
                labels=dict(labels),
                owner_refs=[OwnerRef("ReplicaSet", f"{name}-rs")],
                node_selector=dict(pool),
                spread_constraints=spread,
            ))

        for j in range(2):  # selector-matched residents: za-g count = 2
            add_pod(f"m{j}-{g}", f"spot-a-{g}", matched_cpu,
                    {"app": f"app-{g}"})
        add_pod(f"h0-{g}", f"spot-b-{g}", heavy_total,
                {"bg": f"bg-{g}"})
        # the movers: filler (bigger, sorts first) + the spread carrier
        add_pod(f"filler-{g}", f"od-{g}", filler_cpu,
                {"bg": f"fill-{g}"})
        add_pod(
            f"carrier-{g}", f"od-{g}", carrier_cpu,
            {"app": f"app-{g}"},
            spread=((ZONE_LABEL, 2, (("app", f"app-{g}"),)),),
        )
    return fc


def generate_quality_cluster(spec, seed: int = 0, **fake_kwargs) -> FakeCluster:
    """Dispatch: SyntheticSpec (balanced random fill), ContendedSpec,
    AffinitySpec, or SpreadQualitySpec."""
    if isinstance(spec, ContendedSpec):
        return generate_contended_cluster(spec, seed, **fake_kwargs)
    if isinstance(spec, AffinitySpec):
        return generate_affinity_cluster(spec, seed, **fake_kwargs)
    if isinstance(spec, SpreadQualitySpec):
        return generate_spread_quality_cluster(spec, seed, **fake_kwargs)
    return generate_cluster(spec, seed, **fake_kwargs)


@dataclasses.dataclass
class ReplayEvent:
    at: float  # seconds from start
    kind: str  # "add_spot" | "remove_spot"
    node: Optional[NodeSpec] = None
    node_name: str = ""


def generate_replay(
    spec: SyntheticSpec, n_events: int = 1000, seed: int = 0
) -> Tuple[FakeCluster, List[ReplayEvent]]:
    """Config 5: a base cluster plus a timed stream of spot add/remove
    events (interruption replay, BASELINE.json config 5)."""
    rng = np.random.default_rng(seed + 1)
    fc = generate_cluster(spec, seed, reschedule_evicted=True)
    events: List[ReplayEvent] = []
    t = 0.0
    extra = 0
    live_spot = [n for n in fc.nodes if n.startswith("spot-")]
    for _ in range(n_events):
        t += float(rng.exponential(7.0))
        if rng.random() < 0.5 and live_spot:
            name = live_spot.pop(int(rng.integers(0, len(live_spot))))
            events.append(ReplayEvent(at=t, kind="remove_spot", node_name=name))
        else:
            cpu, mem, cap, eph = SHAPES[rng.integers(0, len(SHAPES))]
            name = f"spot-new-{extra}"
            labels = dict(SPOT_LABELS)
            if spec.spread:
                # real kubelets label every node; churned-in capacity
                # must be reachable by spread-constrained pods
                labels["kubernetes.io/hostname"] = name
                labels["topology.kubernetes.io/zone"] = f"z{extra % 4}"
            node = NodeSpec(
                name=name,
                labels=labels,
                allocatable={CPU: cpu, MEMORY: mem, PODS: cap, EPHEMERAL: eph},
            )
            extra += 1
            live_spot.append(node.name)
            events.append(ReplayEvent(at=t, kind="add_spot", node=node))
    return fc, events
