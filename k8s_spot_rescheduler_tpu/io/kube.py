"""Real-cluster client: the Kubernetes apiserver behind ClusterClient.

The reference talks to the apiserver through client-go — watch-backed
listers (reference rescheduler.go:154-156), per-node pod LISTs with a
``spec.nodeName`` field selector (nodes/nodes.go:129-145), the eviction
subresource (scaler/scaler.go:58), ToBeDeleted taint updates
(scaler/scaler.go:77, 140 via CA ``deletetaint``) and an event sink
(rescheduler.go:327-332). This module is that surface over plain HTTPS
(stdlib urllib — no client library), decoding API objects into the
framework's PodSpec/NodeSpec/PDBSpec.

Config resolution mirrors ``createKubeClient`` (rescheduler.go:304-324):
in-cluster service-account credentials when ``running_in_cluster`` is
set, else a kubeconfig file (current-context, token or client-cert auth).

The read path is polling LISTs rather than watch caches: one LIST of all
pods per tick (partitioned by node client-side) replaces the reference's
N per-node LISTs — fewer round trips at 5k-node scale, same data.
"""

from __future__ import annotations

import base64
import json
import os
import random
import ssl
import tempfile
import time as _time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from k8s_spot_rescheduler_tpu.io.cluster import EvictionError
from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeSpec,
    OwnerRef,
    PDBSpec,
    PodSpec,
    Taint,
    Toleration,
)
from k8s_spot_rescheduler_tpu.utils.quantity import parse_cpu_millis, parse_quantity
from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils import tracing

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# Longest server-sent Retry-After the read-retry loop will honor: flow
# control deserves deference, but a single read must never absorb an
# hour-long header — the control loop's skip-tick/breaker path owns
# outages longer than this.
RETRY_AFTER_CAP = 30.0


def transient_http_error(err: Exception):
    """(retryable, retry_after_s) classification of a request failure.

    Transient — worth a backed-off retry: HTTP 429 (apiserver flow
    control; carries Retry-After) and any 5xx, plus every
    connection-level failure (reset, refused, timeout, TLS handshake
    flake — ``URLError`` and the rest of the ``OSError`` family).
    EXCEPT certificate-verification failures: a misconfigured CA bundle
    or hostname can never succeed on retry, so it surfaces immediately
    instead of burning the full backoff budget on every read.
    Everything else (401/403/404/409, malformed JSON, ...) is a real
    answer, not a flake, and surfaces immediately — retrying a 404
    would only delay the caller's own handling of it."""
    if isinstance(err, urllib.error.HTTPError):
        if err.code == 429 or 500 <= err.code < 600:
            retry_after = None
            try:
                value = err.headers.get("Retry-After") if err.headers else None
                if value is not None:
                    retry_after = float(value)
            except (TypeError, ValueError):
                retry_after = None
            return True, retry_after
        return False, None
    if isinstance(err, ssl.SSLCertVerificationError):
        return False, None
    if isinstance(err, urllib.error.URLError) and isinstance(
        getattr(err, "reason", None), ssl.SSLCertVerificationError
    ):
        return False, None
    if isinstance(err, (urllib.error.URLError, OSError)):
        return True, None
    return False, None


def _decode_quantity(name: str, value) -> int:
    if name == "cpu":
        return parse_cpu_millis(value)
    q = parse_quantity(value)
    return int(q.numerator // q.denominator)


def decode_pod(obj: dict) -> PodSpec:
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    requests: Dict[str, int] = {}
    for container in spec.get("containers", []) or []:
        for name, value in (
            container.get("resources", {}).get("requests", {}) or {}
        ).items():
            requests[name] = requests.get(name, 0) + _decode_quantity(name, value)
    owner_refs = [
        OwnerRef(
            kind=ref.get("kind", ""),
            name=ref.get("name", ""),
            controller=bool(ref.get("controller", False)),
        )
        for ref in meta.get("ownerReferences", []) or []
    ]
    tolerations = [
        Toleration(
            key=t.get("key", ""),
            value=t.get("value", ""),
            operator=t.get("operator", "Equal"),
            effect=t.get("effect", ""),
        )
        for t in spec.get("tolerations", []) or []
    ]
    # constraints beyond the modeled predicate set (PVC/volume topology,
    # affinity shapes outside the canonical forms below) mark the pod
    # conservatively unplaceable — its node can never be proven
    # drainable, never stranded. Modeled, interned as pseudo-taint bits
    # replacing the reference's delegation to the real scheduler
    # (rescheduler.go:344; README.md:103-114): required node-affinity
    # matchExpressions and metadata.name matchFields
    # (masks.NodeAffinityBit), hostname anti-affinity (selector groups),
    # and required positive hostname pod-affinity (masks.PodAffinityBit:
    # only nodes already hosting a match admit the pod).
    affinity = spec.get("affinity") or {}
    node_affinity, naff_unmodeled = decode_node_affinity(
        affinity.get("nodeAffinity") or {}
    )
    # `or "default"` (not a dict default): the native engine normalizes
    # null/empty namespace to "default" too — lockstep for the
    # own-namespace `namespaces` verdict below
    pod_ns = meta.get("namespace") or "default"
    anti_affinity_match, anti_zone_match, anti_unmodeled = decode_anti_affinity(
        affinity.get("podAntiAffinity") or {}, pod_ns
    )
    pod_affinity_match, pod_affinity_zone, paff_unmodeled = decode_pod_affinity(
        affinity.get("podAffinity") or {}, pod_ns
    )
    required_affinity = naff_unmodeled or anti_unmodeled or paff_unmodeled
    # PVC-backed volumes: conservatively unplaceable at decode; the
    # volume-affinity resolver (models/volumes.py) lifts this when every
    # claim proves Bound to a modelable PV. Claims whose names are
    # malformed keep has_pvc set with no resolvable names — never lifted.
    pvc_names = []
    has_pvc = False
    for vol in spec.get("volumes", []) or []:
        if isinstance(vol, dict) and "persistentVolumeClaim" in vol:
            # key presence on a dict volume, like ingest.cc's Obj get
            has_pvc = True
            claim = vol.get("persistentVolumeClaim")
            name = claim.get("claimName") if isinstance(claim, dict) else None
            # sep-byte guard keeps the native blob framing safe, in
            # lockstep with ingest.cc (malformed -> never resolvable)
            if isinstance(name, str) and name and not _has_sep_bytes(name):
                pvc_names.append(name)
            else:
                pvc_names = []
                break
    # Hard topology-spread constraints are scheduling predicates the
    # reference's CheckPredicates enforces (PodTopologySpread plugin,
    # README.md:103-114). The canonical shape is modeled
    # (decode_topology_spread → SpreadBit pseudo-taints in the packers);
    # anything beyond it stays conservatively unplaceable — ignoring a
    # hard constraint would approve drains the real scheduler then
    # refuses, the unsafe direction.
    spread_constraints, hard_spread = decode_topology_spread(
        spec.get("topologySpreadConstraints")
    )
    return PodSpec(
        name=meta.get("name", ""),
        namespace=pod_ns,
        node_name=spec.get("nodeName", "") or "",
        requests=requests,
        priority=int(spec.get("priority", 0) or 0),
        labels=meta.get("labels", {}) or {},
        annotations=meta.get("annotations", {}) or {},
        owner_refs=owner_refs,
        tolerations=tolerations,
        phase=obj.get("status", {}).get("phase", "Running"),
        node_selector=spec.get("nodeSelector", {}) or {},
        anti_affinity_match=anti_affinity_match,
        anti_affinity_zone_match=anti_zone_match,
        pod_affinity_match=pod_affinity_match,
        pod_affinity_zone_match=pod_affinity_zone,
        node_affinity=node_affinity,
        spread_constraints=spread_constraints,
        pvc_names=tuple(pvc_names),
        pvc_resolvable=bool(
            has_pvc and pvc_names and not (required_affinity or hard_spread)
        ),
        unmodeled_constraints=bool(required_affinity or has_pvc or hard_spread),
    )


_NODE_AFFINITY_OPS = ("In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt")

# NodeSelectorRequirement.values are NOT apiserver-validated as label
# values — they may contain the native blob's separator bytes
# (\x1c-\x1f). Such requirements are conservatively unmodeled, in exact
# lockstep with native/ingest.cc has_sep_bytes, so the two decode paths
# can never diverge on them.
_SEP_BYTES = ("\x1c", "\x1d", "\x1e", "\x1f")


def _has_sep_bytes(s: str) -> bool:
    return any(ch in s for ch in _SEP_BYTES)


def decode_node_affinity(node_aff: dict) -> tuple:
    """(canonical terms, unmodeled) for a nodeAffinity object.

    The modeled shape is requiredDuringSchedulingIgnoredDuringExecution
    .nodeSelectorTerms where every term uses matchExpressions with the
    six NodeSelectorOperator values and/or matchFields on
    ``metadata.name`` with In/NotIn (the only field selector k8s
    defines; apiserver validation rejects everything else). Field
    expressions canonicalize with reserved operators FieldIn/FieldNotIn
    so a node LABEL literally named "metadata.name" can never collide
    with the field. Canonical form: terms and the expressions within
    each term sorted, In/NotIn value lists sorted+deduped — so equal
    requirements intern to one pseudo-taint bit. Terms that match
    nothing (empty) are dropped (k8s: a nil/empty term selects no
    objects); if every term drops, the requirement matches no node —
    conservatively unmodeled (same unplaceable effect)."""
    req = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    if not req:
        return (), False
    if not isinstance(req, dict):
        return (), True
    term_list = req.get("nodeSelectorTerms")
    if not isinstance(term_list, list) or not term_list:
        return (), True
    terms = []
    for term in term_list:
        if not isinstance(term, dict):
            return (), True
        exprs_in = term.get("matchExpressions") or []
        fields_in = term.get("matchFields") or []
        if not isinstance(exprs_in, list) or not isinstance(fields_in, list):
            return (), True
        exprs = []
        for e in exprs_in:
            if not isinstance(e, dict):
                return (), True
            key, op = e.get("key"), e.get("operator")
            if not isinstance(key, str) or op not in _NODE_AFFINITY_OPS:
                return (), True
            if _has_sep_bytes(key):
                return (), True
            values = e.get("values") or []
            if not isinstance(values, list) or not all(
                isinstance(v, str) and not _has_sep_bytes(v) for v in values
            ):
                return (), True
            if op in ("Exists", "DoesNotExist"):
                values = ()
            elif op in ("Gt", "Lt"):
                if len(values) != 1:
                    return (), True
                values = tuple(values)
            else:  # In / NotIn with at least one value (k8s validation)
                if not values:
                    return (), True
                values = tuple(sorted(set(values)))
            exprs.append((key, op, values))
        for e in fields_in:
            if not isinstance(e, dict):
                return (), True
            key, op = e.get("key"), e.get("operator")
            # metadata.name is the only node field selector k8s defines
            if key != "metadata.name" or op not in ("In", "NotIn"):
                return (), True
            values = e.get("values") or []
            if not isinstance(values, list) or not values or not all(
                isinstance(v, str) and not _has_sep_bytes(v) for v in values
            ):
                return (), True
            exprs.append(
                (key, "FieldIn" if op == "In" else "FieldNotIn",
                 tuple(sorted(set(values))))
            )
        if exprs:
            terms.append(tuple(sorted(exprs)))
    if not terms:
        return (), True  # all terms match nothing: unplaceable
    return tuple(sorted(set(terms))), False


from k8s_spot_rescheduler_tpu.predicates.masks import (
    ZONE_LABEL as ZONE_TOPOLOGY_KEY,
)


from k8s_spot_rescheduler_tpu.predicates.selectors import (
    ALL_NAMESPACES,
    SELECTOR_OPS as _SELECTOR_OPS,
    canon_selector,
    selector_matches_nothing,
)


def _decode_term(term: dict, namespace: str):
    """One required pod-affinity term, canonicalized to the round-5
    widened shape (predicates/selectors.py): a ``(namespaces, selector)``
    term with the full LabelSelector operator surface. Exact native
    lockstep (native/ingest.cc ``term_selector_blob``):

    - ``namespaces`` absent/empty resolves to the pod's own namespace;
      an explicit list of namespace names (cross-namespace included) is
      modeled as the term's scope — k8s semantics: the list REPLACES
      the own-namespace default, it does not extend it;
    - ``namespaceSelector: {}`` selects EVERY namespace (k8s) and is
      modeled as the wildcard scope (selectors.ALL_NAMESPACES — it
      subsumes any ``namespaces`` list, whose union with all-namespaces
      is all-namespaces); a NON-empty namespaceSelector matches
      namespace LABELS, which this framework does not observe, and
      stays unmodeled;
    - ``matchLabels`` pairs become single-value In requirements;
    - ``matchExpressions`` entries model In / NotIn / Exists /
      DoesNotExist with multi-value lists; In/NotIn need >=1 value and
      Exists/DoesNotExist must carry none (k8s validation);
    - an empty selector stays unmodeled; separator bytes anywhere stay
      unmodeled (native blob framing, has_sep_bytes lockstep).

    Returns (term | None, matches_nothing, unmodeled)."""
    ns_list = term.get("namespaces")
    if ns_list:
        # "*" is reserved as the all-namespaces sentinel (DNS labels
        # cannot contain it); a literal "*" entry is malformed and must
        # not silently widen the scope
        if not isinstance(ns_list, list) or not all(
            isinstance(x, str) and x and x != "*" and not _has_sep_bytes(x)
            for x in ns_list
        ):
            return None, False, True
        namespaces = tuple(sorted(set(ns_list)))
    else:
        namespaces = (namespace,)
    if "namespaceSelector" in term:
        ns_sel = term["namespaceSelector"]
        if ns_sel == {}:
            # k8s: an empty namespaceSelector selects EVERY namespace;
            # the union with any `namespaces` list is still everything
            namespaces = ALL_NAMESPACES
        elif ns_sel is not None:
            # non-empty selectors match namespace LABELS, which this
            # framework does not observe — conservatively unmodeled.
            # null is the API's explicit "no selector" (≡ absent).
            return None, False, True
    sel = term.get("labelSelector")
    if not isinstance(sel, dict):
        return None, False, True
    match = sel.get("matchLabels")
    if match is None:
        match = {}
    if not isinstance(match, dict):
        return None, False, True
    if any(
        not isinstance(k, str) or not isinstance(v, str)
        or _has_sep_bytes(k) or _has_sep_bytes(v)
        for k, v in match.items()
    ):
        return None, False, True
    reqs = [(k, "In", (v,)) for k, v in match.items()]
    exprs = sel.get("matchExpressions")
    if exprs:
        if not isinstance(exprs, list):
            return None, False, True
        for e in exprs:
            if not isinstance(e, dict):
                return None, False, True
            key, op = e.get("key"), e.get("operator")
            if (
                not isinstance(key, str)
                or _has_sep_bytes(key)
                or op not in _SELECTOR_OPS
            ):
                return None, False, True
            values = e.get("values")
            if op in ("Exists", "DoesNotExist"):
                if values:  # k8s validation: no values for these ops
                    return None, False, True
                reqs.append((key, op, ()))
                continue
            if not isinstance(values, list) or not values or not all(
                isinstance(v, str) and not _has_sep_bytes(v) for v in values
            ):
                return None, False, True
            reqs.append((key, op, tuple(sorted(set(values)))))
    if not reqs:
        return None, False, True  # empty selector: not modeled
    selector = canon_selector(reqs)
    return (namespaces, selector), selector_matches_nothing(selector), False


def decode_anti_affinity(anti: dict, namespace: str = "default") -> tuple:
    """(hostname terms, zone terms, unmodeled) for a podAntiAffinity
    object — round-5 widened canonical shape, in exact lockstep with
    native/ingest.cc ``extract_anti_affinity``: ANY number of required
    terms, each hostname or zone topology, each with the widened
    ``_decode_term`` selector (full operator surface + cross-namespace
    scopes). A term whose selector matches nothing constrains nothing
    and is dropped exactly; any other topology key stays unmodeled."""
    req = anti.get("requiredDuringSchedulingIgnoredDuringExecution")
    if not req:
        return (), (), False
    if not isinstance(req, list):
        return (), (), True
    host: list = []
    zone: list = []
    for term in req:
        if not isinstance(term, dict):
            return (), (), True
        topo = term.get("topologyKey")
        if topo == "kubernetes.io/hostname":
            out = host
        elif topo == ZONE_TOPOLOGY_KEY:
            out = zone
        else:
            return (), (), True
        decoded, nothing, unmodeled = _decode_term(term, namespace)
        if unmodeled:
            return (), (), True
        if nothing:
            continue  # constrains nothing — exact to drop
        out.append(decoded)
    return tuple(sorted(set(host))), tuple(sorted(set(zone))), False


def decode_pod_affinity(paff: dict, namespace: str = "default") -> tuple:
    """(hostname terms, zone terms, unmodeled) for a required POSITIVE
    podAffinity object — round 5: ANY number of required terms, each
    hostname or zone topology, each with the widened selector; every
    term must hold. Hostname: the pod may only join a node already
    hosting a match (masks.PodAffinityBit); zone: a ZONE already
    hosting a match (masks.ZonePodAffinityBit). A never-matching
    selector is KEPT as a term: no resident can ever match it, so every
    node refuses the carrier — exactly the scheduler's verdict for an
    unsatisfiable positive requirement."""
    req = paff.get("requiredDuringSchedulingIgnoredDuringExecution")
    if not req:
        return (), (), False
    if not isinstance(req, list):
        return (), (), True
    host: list = []
    zone: list = []
    for term in req:
        if not isinstance(term, dict):
            return (), (), True
        topo = term.get("topologyKey")
        if topo == "kubernetes.io/hostname":
            out = host
        elif topo == ZONE_TOPOLOGY_KEY:
            out = zone
        else:
            return (), (), True
        decoded, _nothing, unmodeled = _decode_term(term, namespace)
        if unmodeled:
            return (), (), True
        out.append(decoded)
    return tuple(sorted(set(host))), tuple(sorted(set(zone))), False


# Fields whose NON-DEFAULT values change PodTopologySpread counting
# semantics in ways this model does not reproduce. Round 5: an explicit
# DEFAULT value is semantically identical to the field being absent and
# is accepted (common in manifests that spell out defaults) — the
# model's existing conservatism analysis already covers the default
# semantics: nodeTaintsPolicy=Ignore IS how the counts are computed
# (dead/tainted nodes' domains and pods counted), and
# nodeAffinityPolicy=Honor is deliberately over-approximated (ignoring
# the affinity filter only ever lowers the domain min — stricter, the
# safe direction). minDomains=null and matchLabelKeys=[] are the
# absent-equivalent encodings of their fields. Anything else stays
# conservatively unmodeled.
def _spread_modifiers_default(c: dict) -> bool:
    """True iff every present counting-modifier field carries its
    default-equivalent value (exact lockstep with native/ingest.cc
    ``spread_modifier_is_default``): minDomains null / integer 1 (nil
    behaves as 1 per KEP-3022 — a non-int 1.0 is rejected, matching
    the native text comparison), matchLabelKeys null / [],
    nodeAffinityPolicy null / "Honor", nodeTaintsPolicy null /
    "Ignore"."""
    if "minDomains" in c:
        v = c["minDomains"]
        if v is not None and not (
            isinstance(v, int) and not isinstance(v, bool) and v == 1
        ):
            return False
    if "matchLabelKeys" in c:
        v = c["matchLabelKeys"]
        if v is not None and v != []:
            return False
    if "nodeAffinityPolicy" in c:
        v = c["nodeAffinityPolicy"]
        if v is not None and v != "Honor":
            return False
    if "nodeTaintsPolicy" in c:
        v = c["nodeTaintsPolicy"]
        if v is not None and v != "Ignore":
            return False
    return True
# Spread topology is generic: the verdict machinery keys counts and
# domains by the constraint's OWN topology key (masks.SpreadBit /
# compute_spread_bit read node.labels[topology_key] directly), so ANY
# label key works — unlike zone anti-affinity, whose zone-salted
# machinery is specific to the standard zone label. Round 5 lifts the
# hostname/zone-only restriction; the key only needs to be a non-empty
# sep-byte-free string (native blob framing).


def decode_topology_spread(spread) -> tuple:
    """(canonical hard constraints, unmodeled) for a pod's
    topologySpreadConstraints list.

    Modeled (in exact lockstep with native/ingest.cc): each HARD entry
    (whenUnsatisfiable absent or DoNotSchedule — the k8s default) with
    ANY non-empty sep-free topologyKey (round 5 — the SpreadBit
    machinery is generic over the key), integer maxSkew >= 1, a non-empty
    selector in the round-5 widened operator form (matchLabels and/or
    matchExpressions with In/NotIn/Exists/DoesNotExist; spread is
    always own-namespace per the k8s API), and counting-semantics
    modifier fields only at their default-equivalent values
    (``_spread_modifiers_default``). Explicit ScheduleAnyway
    entries are soft — advisory to the real scheduler — and dropped.
    Any hard entry beyond the canonical shape marks the whole pod
    unmodeled (conservatively unplaceable). Canonical form:
    (topology_key, max_skew, selector requirements), entry list
    sorted+deduped. A never-matching selector needs no special case:
    its domain counts are all zero, so its verdict refuses nothing —
    exactly the scheduler's behavior."""
    if not spread:
        return (), False
    if not isinstance(spread, list):
        return (), True
    out = []
    for c in spread:
        if not isinstance(c, dict):
            return (), True
        if c.get("whenUnsatisfiable", "DoNotSchedule") == "ScheduleAnyway":
            continue  # soft: the scheduler only prefers, never refuses
        if not _spread_modifiers_default(c):
            return (), True
        topo = c.get("topologyKey")
        if not isinstance(topo, str) or not topo or _has_sep_bytes(topo):
            return (), True
        skew = c.get("maxSkew")
        if not isinstance(skew, int) or isinstance(skew, bool) or skew < 1:
            return (), True
        decoded, _nothing, unmodeled = _decode_term(
            {"labelSelector": c.get("labelSelector")}, "default"
        )
        if unmodeled:
            return (), True
        out.append((topo, skew, decoded[1]))
    return tuple(sorted(set(out))), False


def decode_volume_snapshots(pvc_items, pv_items) -> tuple:
    """(pvc-by-uid, pv-by-name) maps from decoded LIST items — THE
    keying convention ``models/volumes.resolve_volume_affinity`` reads;
    shared by the polling client and the planner sidecar so the two
    can never drift."""
    pvcs = {(c := decode_pvc(o)).uid: c for o in pvc_items}
    pvs = {(v := decode_pv(o)).name: v for o in pv_items}
    return pvcs, pvs


def decode_pvc(obj: dict) -> "PVCSpec":
    from k8s_spot_rescheduler_tpu.models.cluster import PVCSpec

    meta = obj.get("metadata", {})
    return PVCSpec(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        volume_name=(obj.get("spec", {}) or {}).get("volumeName", "") or "",
        phase=(obj.get("status", {}) or {}).get("phase", "") or "",
    )


def decode_pv(obj: dict) -> "PVSpec":
    """PV node-affinity (spec.nodeAffinity.required is a plain
    NodeSelector) reuses the pod-side canonicalizer by wrapping it in the
    requiredDuringScheduling envelope — identical modeled/unmodeled
    rules, so PV terms can merge straight into pod terms."""
    from k8s_spot_rescheduler_tpu.models.cluster import PVSpec

    meta = obj.get("metadata", {})
    naff = (obj.get("spec", {}) or {}).get("nodeAffinity")
    terms: tuple = ()
    unmodeled = False
    if naff is not None:
        if not isinstance(naff, dict):
            unmodeled = True
        else:
            required = naff.get("required")
            if required is not None:
                if not required:
                    # present-but-empty NodeSelector: the scheduler's
                    # matcher treats non-nil empty terms as matching NO
                    # node — resolving it as "no constraint" would be
                    # the unsafe direction, so: unmodeled
                    unmodeled = True
                else:
                    terms, unmodeled = decode_node_affinity(
                        {"requiredDuringSchedulingIgnoredDuringExecution":
                             required}
                    )
    return PVSpec(
        name=meta.get("name", ""),
        node_affinity=terms,
        unmodeled=unmodeled,
    )


def decode_node(obj: dict) -> NodeSpec:
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    status = obj.get("status", {})
    allocatable = {
        name: _decode_quantity(name, value)
        for name, value in (status.get("allocatable", {}) or {}).items()
    }
    taints = [
        Taint(t.get("key", ""), t.get("value", ""), t.get("effect", "NoSchedule"))
        for t in spec.get("taints", []) or []
    ]
    ready = any(
        c.get("type") == "Ready" and c.get("status") == "True"
        for c in status.get("conditions", []) or []
    )
    return NodeSpec(
        name=meta.get("name", ""),
        labels=meta.get("labels", {}) or {},
        allocatable=allocatable,
        taints=taints,
        ready=ready,
        unschedulable=bool(spec.get("unschedulable", False)),
    )


def decode_pdb(obj: dict) -> PDBSpec:
    """Round 5: the PDB selector parses the full
    matchLabels/matchExpressions surface via the shared term decoder.
    Shapes beyond it fall back to the EMPTY selector — which for a PDB
    means "every pod in the namespace", the conservative direction (an
    unparseable PDB must block drains, never under-protect; the
    apiserver additionally enforces PDBs on the eviction subresource,
    so this conservatism costs drains, not safety)."""
    from k8s_spot_rescheduler_tpu.predicates.selectors import MATCH_NOTHING

    meta = obj.get("metadata", {})
    sel = (obj.get("spec", {}) or {}).get("selector")
    if sel is None:
        # policy/v1: a NIL selector selects zero pods
        # (labels.Nothing()) — distinct from {} which selects all
        reqs: tuple = MATCH_NOTHING
    else:
        decoded, _nothing, unmodeled = _decode_term(
            {"labelSelector": sel if isinstance(sel, dict) else {}},
            "default",
        )
        if unmodeled:
            # empty selector ({} -> select-all) is also routed here by
            # the term decoder (it refuses empty selectors); both land
            # on the conservative select-all shape a PDB defines for {}
            reqs = ()
        else:
            reqs = decoded[1]
    return PDBSpec(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        match_labels=reqs,
        disruptions_allowed=int(
            obj.get("status", {}).get("disruptionsAllowed", 0) or 0
        ),
    )


class KubeClusterClient:
    """ClusterClient + EventSink over the apiserver REST API."""

    def __init__(
        self,
        base_url: str,
        *,
        token: str = "",
        token_file: str = "",
        ca_file: str = "",
        client_cert: str = "",
        client_key: str = "",
        insecure: bool = False,
        retry_max: int = 4,
        retry_base: float = 0.25,
        retry_sleep=None,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        # Transient-failure retry policy for READ verbs (GET): up to
        # retry_max additional attempts with jittered exponential backoff
        # from retry_base seconds, honoring Retry-After. Writes (evict /
        # taint / events) stay single-attempt: the actuator owns their
        # retry cadence (scaler.go:47-62), and a blind HTTP-level re-send
        # could double-apply a non-idempotent mutation.
        self.retry_max = int(retry_max)
        self.retry_base = float(retry_base)
        self._retry_sleep = retry_sleep or _time.sleep
        # private urandom-seeded instance: jitter must decorrelate
        # replicas/restarts (a fixed seed would synchronize the herd it
        # exists to spread) without perturbing global random state
        self._retry_rng = random.Random()
        # projected SA tokens rotate on disk (~1h TTL); when reading from a
        # file, re-read per request like client-go does
        self.token_file = token_file
        ctx = ssl.create_default_context(
            cafile=ca_file if ca_file else None
        )
        if client_cert:
            ctx.load_cert_chain(client_cert, client_key or None)
        if insecure:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        self._ctx = ctx
        # one LIST of all pods per tick, partitioned client-side
        self._pods_cache: Optional[Dict[str, List[PodSpec]]] = None
        # one LIST of all nodes per tick, split by readiness: the ready
        # and unready views MUST come from one snapshot — two separate
        # LISTs could miss a node flipping unready->ready between them,
        # silently dropping its pods from spread/zone presence (the
        # permissive direction; advisor r4)
        self._nodes_cache: Optional[tuple] = None
        # native LIST decoding (io/native_ingest.py); the CLI clears this
        # when the configured resources exceed the native schema
        self.use_native_ingest = True

    # --- plumbing ---

    def _open(self, method: str, path: str, body: Optional[dict],
              timeout: float):
        """Authorized HTTP round trip; returns the open response."""
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if body is not None:
            # merge-patch replaces lists wholesale — required for taint
            # removal (strategic merge keeps omitted keyed list entries)
            content_type = (
                "application/merge-patch+json"
                if method == "PATCH"
                else "application/json"
            )
            req.add_header("Content-Type", content_type)
        token = self.token
        if self.token_file:
            with open(self.token_file) as fh:
                token = fh.read().strip()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        ctx = self._ctx if url.startswith("https") else None
        return urllib.request.urlopen(req, context=ctx, timeout=timeout)

    def _read_retrying(self, method: str, path: str, timeout: float) -> bytes:
        """One read request (open + body), retried with jittered
        exponential backoff on transient failures (429/5xx/connection —
        ``transient_http_error``). Honors Retry-After when the server
        sends one (the backoff never undercuts it). Each retry bumps
        ``kube_request_retries_total``; exhausting the budget bumps
        ``kube_request_failures_total`` and re-raises, at which point the
        control loop's observe-error policy skips the tick."""
        from k8s_spot_rescheduler_tpu.metrics import registry as metrics

        attempt = 0
        # one span per kube READ, retries included (attempts attr):
        # the tick trace shows which apiserver call a slow observe
        # actually waited on. The path attr is redacted at dump time
        # (it can carry namespaces/pod names).
        with tracing.span("kube.get", path=path) as sp:
            while True:
                try:
                    with self._open(
                        method, path, None, timeout=timeout
                    ) as resp:
                        body = resp.read()
                    if sp is not None and attempt:
                        sp.attrs["attempts"] = attempt + 1
                    return body
                except Exception as err:  # noqa: BLE001 — classified below
                    retryable, retry_after = transient_http_error(err)
                    if not retryable:
                        raise
                    if attempt >= self.retry_max:
                        metrics.update_kube_request_failure()
                        raise
                    # full jitter around the exponential midpoint: delay
                    # in [0.5, 1.5) x base x 2^attempt, floored by
                    # Retry-After — capped: one bad header (a degraded
                    # LB answering "Retry-After: 3600") must not stall
                    # the tick for hours inside a single read; past the
                    # cap the error surfaces through the
                    # observe-skip/breaker machinery instead
                    delay = self.retry_base * (2.0 ** attempt)
                    delay *= 0.5 + self._retry_rng.random()
                    if retry_after is not None:
                        delay = max(delay, min(retry_after, RETRY_AFTER_CAP))
                    metrics.update_kube_request_retry()
                    log.vlog(
                        2,
                        "kube %s %s failed transiently (%s); "
                        "retry %d/%d in %.2fs",
                        method, path, err, attempt + 1, self.retry_max,
                        delay,
                    )
                    self._retry_sleep(delay)
                    attempt += 1

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        *,
        retries: bool = True,
    ):
        """``retries=False`` opts a READ out of the backoff loop —
        deadline-bound callers (the lease elector, whose renew cadence
        IS its retry policy and whose lease must not absorb backoff
        sleeps) handle transient failures themselves."""
        if retries and method == "GET" and body is None:
            payload = self._read_retrying("GET", path, timeout=30)
        else:
            # write verbs: single attempt (see __init__ on retry policy)
            with self._open(method, path, body, timeout=30) as resp:
                payload = resp.read()
        return json.loads(payload) if payload else {}

    def _request_raw(self, method: str, path: str) -> bytes:
        """Raw response bytes — the native ingest engine parses LIST
        bodies itself (io/native_ingest.py). Reads only: the retrying
        path must never carry a write verb (a retried write double-fires
        its side effect on a timeout whose request actually landed)."""
        if method != "GET":
            raise ValueError(
                f"_request_raw is read-only; {method} must go through "
                "_request"
            )
        return self._read_retrying("GET", path, timeout=60)

    def _stream(self, path: str, read_timeout: float = 330.0):
        """Yield newline-delimited JSON objects from a watch endpoint.

        The timeout exceeds the watch's own ``timeoutSeconds`` so an idle
        but healthy stream is closed by the server, not by us; the caller
        (io/watch.py) reconnects from the last resourceVersion either way.
        """
        with self._open("GET", path, None, timeout=read_timeout) as resp:
            for line in resp:
                line = line.strip()
                if line:
                    yield json.loads(line)

    # --- read path ---

    def refresh(self) -> None:
        """Invalidate the per-tick pod/node caches. The control loop's
        first read each tick is ``list_unschedulable_pods`` (the safety
        gate), which refreshes — so every tick sees one consistent pod
        LIST and one consistent node LIST."""
        self._pods_cache = None
        self._nodes_cache = None

    def _all_nodes(self) -> tuple:
        """(ready, unready) node views from ONE GET /api/v1/nodes per
        tick — a single snapshot split by readiness, so a node flipping
        between the two reads can never vanish from both views (and the
        heaviest LIST is paid once, not twice)."""
        if self._nodes_cache is None:
            from k8s_spot_rescheduler_tpu.io import native_ingest

            nodes = None
            if self.use_native_ingest and native_ingest.available():
                batch = native_ingest.parse_node_list(
                    self._request_raw("GET", "/api/v1/nodes")
                )
                if batch is not None:
                    nodes = batch.views()
            if nodes is None:
                items = self._request("GET", "/api/v1/nodes").get("items", [])
                nodes = [decode_node(o) for o in items]
            self._nodes_cache = (
                [n for n in nodes if n.ready],
                [n for n in nodes if not n.ready],
            )
        return self._nodes_cache

    def list_ready_nodes(self) -> List[NodeSpec]:
        # the reference's ReadyNodeLister surfaces only ready nodes
        return list(self._all_nodes()[0])

    def list_unready_nodes(self) -> List[NodeSpec]:
        """Presence-only node view (NodeMap.unready): zone/spread counts
        must span not-ready nodes' pods (they still exist to the real
        scheduler; PodTopologySpread's default nodeTaintsPolicy=Ignore
        counts their domains)."""
        return list(self._all_nodes()[1])

    def _all_pods(self) -> Dict[str, List[PodSpec]]:
        if self._pods_cache is None:
            from k8s_spot_rescheduler_tpu.io import native_ingest

            pods = None
            pvc_hint = None
            if self.use_native_ingest and native_ingest.available():
                batch = native_ingest.parse_pod_list(
                    self._request_raw("GET", "/api/v1/pods")
                )
                if batch is not None:
                    pods = batch.views()
                    # exact vectorized "any pod is resolvable" — not just
                    # "any pod has a PVC", which would send every tick of
                    # a PVC-carrying cluster through a 50k-view Python
                    # scan below (advisor r3)
                    pvc_hint = batch.any_pvc_resolvable()
            if pods is None:
                items = self._request("GET", "/api/v1/pods").get("items", [])
                pods = [decode_pod(obj) for obj in items]
            pods = self._resolve_volumes(pods, pvc_hint)
            cache: Dict[str, List[PodSpec]] = {}
            for pod in pods:
                cache.setdefault(pod.node_name, []).append(pod)
            self._pods_cache = cache
        return self._pods_cache

    def list_volume_snapshots(self):
        """(pvc-by-uid, pv-by-name) decoded from cluster-wide LISTs —
        shared by this client's polling path and the watch-mode client's
        per-tick retry. Raises on HTTP/decode failure; callers stay
        conservative."""
        return decode_volume_snapshots(
            self._request(
                "GET", "/api/v1/persistentvolumeclaims"
            ).get("items", []),
            self._request(
                "GET", "/api/v1/persistentvolumes"
            ).get("items", []),
        )

    def _resolve_volumes(self, pods, pvc_hint=None):
        """Lift PVC-pod conservatism where provable: fetch same-tick
        PVC/PV LISTs (only when some pod actually carries resolvable
        claims) and fold bound PVs' nodeAffinity into the pods
        (models/volumes.py). Any fetch/decode failure leaves the pods as
        decoded — placeable nowhere, the safe direction. ``pvc_hint``
        (the native batch path precomputes it vectorized, exactly the
        PodView.pvc_resolvable predicate) is authoritative in BOTH
        directions: False skips the per-pod scan entirely, True skips
        the redundant re-check — 50k lazy property reads per tick would
        cost real time on the hot path."""
        if pvc_hint is False:
            return pods
        if pvc_hint is None and not any(
            getattr(p, "pvc_resolvable", False) for p in pods
        ):
            return pods
        from k8s_spot_rescheduler_tpu.models.volumes import (
            maybe_resolve_view,
            resolve_volume_affinity,
        )

        try:
            pvcs, pvs = self.list_volume_snapshots()
        except Exception as err:  # noqa: BLE001, exception-discipline — stay conservative: the pods remain unmodeled (the SAFE direction, blocked_candidates 'unmodeled' surfaces it) and the retry layer already counted the read failure
            log.error("PVC/PV list failed; volume pods stay unmodeled: %s", err)
            return pods
        out = []
        for pod in pods:
            if isinstance(pod, PodSpec):
                out.append(resolve_volume_affinity(pod, pvcs, pvs))
            else:  # lazy native view: materialize only if it resolves
                out.append(maybe_resolve_view(pod, pvcs, pvs) or pod)
        return out

    def list_pods_on_node(self, node_name: str) -> List[PodSpec]:
        return list(self._all_pods().get(node_name, []))

    def list_unschedulable_pods(self) -> List[PodSpec]:
        # reference NewUnschedulablePodLister: pending pods with no node.
        # The control loop calls this FIRST each tick (the safety gate), so
        # it must refresh the per-tick pod cache — a stale view here would
        # let a drain proceed while pods are already unschedulable.
        self.refresh()
        return [
            p
            for p in self._all_pods().get("", [])
            if p.phase == "Pending"
        ]

    def list_pdbs(self) -> List[PDBSpec]:
        items = self._request(
            "GET", "/apis/policy/v1/poddisruptionbudgets"
        ).get("items", [])
        return [decode_pdb(o) for o in items]

    def get_pod(self, namespace: str, name: str) -> Optional[PodSpec]:
        # single-attempt: the only production caller is the drain verify
        # poll (actuator/drain.py), which already re-polls every 5 s per
        # pod until its own deadline — stacking the transport retry
        # budget under it would let one poll round overshoot
        # pod_eviction_timeout by pods x backoff
        try:
            obj = self._request(
                "GET", f"/api/v1/namespaces/{namespace}/pods/{name}",
                retries=False,
            )
        except urllib.error.HTTPError as err:
            if err.code == 404:
                return None
            raise
        return decode_pod(obj)

    # --- write path ---

    def evict_pod(self, pod: PodSpec, grace_seconds: int) -> None:
        body = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": pod.name, "namespace": pod.namespace},
            "deleteOptions": {"gracePeriodSeconds": int(grace_seconds)},
        }
        try:
            self._request(
                "POST",
                f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}/eviction",
                body,
            )
        except urllib.error.HTTPError as err:
            if err.code == 404:
                return  # already gone
            raise EvictionError(f"evict {pod.uid}: HTTP {err.code}") from err

    def _patch_taints(self, node_name: str, mutate) -> None:
        obj = self._request("GET", f"/api/v1/nodes/{node_name}")
        taints = (obj.get("spec", {}).get("taints", []) or [])
        self._request(
            "PATCH",
            f"/api/v1/nodes/{node_name}",
            {"spec": {"taints": mutate(taints)}},
        )

    def add_taint(self, node_name: str, taint: Taint) -> None:
        from k8s_spot_rescheduler_tpu.models.cluster import (
            parse_rescheduler_taint_value,
        )

        def mutate(taints):
            entry = {"key": taint.key, "value": taint.value, "effect": taint.effect}
            # Same-key entry we own (or an empty value): REPLACE it — a
            # re-drain must refresh the ownership stamp, or the stale
            # one ages past the sweep's grace horizon under a live
            # drain. Same-key entry held by a FOREIGN writer (the
            # cluster autoscaler's bare-timestamp scale-down marker):
            # keep THEIRS untouched — overwriting would convert CA's
            # taint into one our orphan sweep may later remove,
            # aborting CA's node deletion.
            for t in taints:
                if t.get("key") != taint.key:
                    continue
                value = t.get("value") or ""
                if value and parse_rescheduler_taint_value(value) is None:
                    return taints  # foreign holder: leave their entry
            return [t for t in taints if t.get("key") != taint.key] + [entry]

        self._patch_taints(node_name, mutate)

    def remove_taint(self, node_name: str, taint_key: str) -> None:
        self._patch_taints(
            node_name,
            lambda taints: [t for t in taints if t.get("key") != taint_key],
        )

    # --- event sink (reference createEventRecorder, rescheduler.go:327) ---

    def event(
        self, kind: str, name: str, event_type: str, reason: str, message: str
    ) -> None:
        namespace = "default"
        obj_name = name
        if kind == "Pod" and "/" in name:
            namespace, obj_name = name.split("/", 1)
        body = {
            "metadata": {"generateName": "spot-rescheduler-"},
            "involvedObject": {"kind": kind, "name": obj_name,
                               "namespace": namespace if kind == "Pod" else ""},
            "type": event_type,
            "reason": reason,
            "message": message,
            "source": {"component": "rescheduler"},
        }
        try:
            self._request(
                "POST", f"/api/v1/namespaces/{namespace}/events", body
            )
        except Exception as err:  # noqa: BLE001, exception-discipline — events are best-effort decoration by contract (the reference's recorder is fire-and-forget too); nothing degrades when one is lost
            log.vlog(4, "event post failed: %s", err)


def from_environment(
    running_in_cluster: bool, kubeconfig: str = ""
) -> KubeClusterClient:
    """createKubeClient equivalent (reference rescheduler.go:304-324)."""
    if running_in_cluster:
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        return KubeClusterClient(
            f"https://{host}:{port}",
            token_file=os.path.join(SA_DIR, "token"),
            ca_file=os.path.join(SA_DIR, "ca.crt"),
        )

    import yaml

    kubeconfig = kubeconfig or os.path.expanduser("~/.kube/config")
    with open(kubeconfig) as fh:
        cfg = yaml.safe_load(fh)
    ctx_name = cfg.get("current-context")
    ctx = next(
        c["context"] for c in cfg.get("contexts", []) if c["name"] == ctx_name
    )
    cluster = next(
        c["cluster"]
        for c in cfg.get("clusters", [])
        if c["name"] == ctx["cluster"]
    )
    user = next(
        u["user"] for u in cfg.get("users", []) if u["name"] == ctx["user"]
    )

    def materialize(data_key: str, file_key: str, blob: dict) -> str:
        if file_key in blob:
            return blob[file_key]
        if data_key in blob:
            fh = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
            fh.write(base64.b64decode(blob[data_key]))
            fh.close()
            return fh.name
        return ""

    return KubeClusterClient(
        cluster["server"],
        token=user.get("token", ""),
        ca_file=materialize(
            "certificate-authority-data", "certificate-authority", cluster
        ),
        client_cert=materialize(
            "client-certificate-data", "client-certificate", user
        ),
        client_key=materialize("client-key-data", "client-key", user),
        insecure=bool(cluster.get("insecure-skip-tls-verify", False)),
    )
