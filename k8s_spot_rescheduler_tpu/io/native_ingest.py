"""ctypes bindings for the native ingest engine (native/ingest.cc).

The engine parses apiserver LIST JSON (50k pods ~= 30 MB) into columnar
batches in one native pass — ~10x the pure-Python ``json.loads`` +
``decode_pod`` path. Rows come back as numpy arrays plus a shared string
heap; pods/nodes are wrapped in **lazy views** (``PodView``/``NodeView``)
that quack like ``models/cluster.PodSpec``/``NodeSpec`` but only
materialize dicts (requests, labels) on first access — the solver path
reads the numeric columns and never touches them.

Optional: ``available()`` is False when the shared library hasn't been
built (``make native``) and callers fall back to the pure-Python decode
(io/kube.py ``decode_pod``/``decode_node``), which stays the semantic
reference — ``tests/test_native_ingest.py`` pins the two together
differentially, quantity grammar corner cases included.
"""

from __future__ import annotations

import ctypes
import functools
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from k8s_spot_rescheduler_tpu.models.cluster import (
    MIRROR_POD_ANNOTATION,
    NodeSpec,
    OwnerRef,
    PodSpec,
    Taint,
    Toleration,
)

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
    "_ingest.so",
)

_UNIT = "\x1f"
_REC = "\x1e"
_TERM = "\x1d"  # node-affinity blob: term separator (ingest.cc TERM_SEP)
_VAL = "\x1c"  # node-affinity blob: In/NotIn value separator (VAL_SEP)

# pod flag bits (native/ingest.cc)
F_MIRROR, F_DAEMONSET, F_REPLICATED, F_TERMINAL, F_PENDING = 1, 2, 4, 8, 16
F_PVC, F_REQAFF = 32, 64
# pod column indices
P_CPU, P_MEM, P_EPH = 0, 1, 2
(P_PRIO, P_NODEID, P_NSID, P_TOLID, P_LABELSID, P_SELID,
 P_AAFFID, P_NAFFID, P_PAFFID, P_ZAFFID, P_PVCID, P_SPREADID,
 P_PZAFFID) = range(13)
PS_NAME, PS_UID = range(2)
# interned-table families
(TBL_NODE, TBL_NS, TBL_TOLS, TBL_LABELS, TBL_NODESEL, TBL_AAFF,
 TBL_NAFF, TBL_PAFF, TBL_ZAFF, TBL_PVC, TBL_SPREAD, TBL_PZAFF) = range(12)
# node column indices
N_CPU, N_MEM, N_EPH, N_PODS = range(4)
N_READY, N_UNSCHED, N_HASPODS = range(3)
NS_NAME, NS_UID, NS_LABELS, NS_TAINTS = range(4)


@functools.lru_cache(maxsize=1)
def _lib() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.ingest_pods.restype = ctypes.c_void_p
    lib.ingest_pods.argtypes = [ctypes.c_char_p, ctypes.c_long]
    lib.ingest_nodes.restype = ctypes.c_void_p
    lib.ingest_nodes.argtypes = [ctypes.c_char_p, ctypes.c_long]
    lib.ingest_free.argtypes = [ctypes.c_void_p]
    lib.batch_count.restype = ctypes.c_long
    lib.batch_count.argtypes = [ctypes.c_void_p]
    for name in ("batch_i64", "batch_str"):
        fn = getattr(lib, name)
        fn.restype = ctypes.POINTER(ctypes.c_int64)
        fn.argtypes = [ctypes.c_void_p]
    lib.batch_i32.restype = ctypes.POINTER(ctypes.c_int32)
    lib.batch_i32.argtypes = [ctypes.c_void_p]
    lib.batch_u8.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.batch_u8.argtypes = [ctypes.c_void_p]
    lib.batch_heap.restype = ctypes.POINTER(ctypes.c_char)
    lib.batch_heap.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)]
    lib.batch_rv.restype = ctypes.c_char_p
    lib.batch_rv.argtypes = [ctypes.c_void_p]
    lib.batch_table.restype = ctypes.POINTER(ctypes.c_int64)
    lib.batch_table.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_long),
    ]
    # ABI handshake: a stale .so built for an older column layout would be
    # silently misread — verify the self-described layout and refuse
    # (callers fall back to the Python decoders) on any mismatch.
    try:
        ok = (
            lib.pod_ncols_i64() == 3
            and lib.pod_ncols_i32() == 13
            and lib.pod_ncols_u8() == 1
            and lib.pod_ncols_str() == 2
            and lib.node_ncols_i64() == 4
            and lib.node_ncols_u8() == 3
            and lib.node_ncols_str() == 4
            and lib.table_count() == 12
            # the acceptance version covers blob format AND the
            # modeled/unmodeled decision surface: a stale .so would
            # silently disagree with the Python reference decoder
            and lib.blob_format_version() == 3
        )
    except AttributeError:
        ok = False
    if not ok:
        return None
    return lib


def available() -> bool:
    return _lib() is not None


# The native schema carries exactly the resources the framework plans on;
# exotic resources (e.g. extended/GPU) must take the Python decode path,
# which preserves arbitrary request/allocatable keys.
SUPPORTED_RESOURCES = frozenset({"cpu", "memory", "ephemeral-storage", "pods"})


def supports(resources) -> bool:
    """True if the native schema carries every configured resource."""
    return set(resources) <= SUPPORTED_RESOURCES


def _copy_batch(lib, handle, ni64: int, ni32: int, nu8: int, nstr: int,
                tables: int = 0):
    """Copy the batch arrays out of native memory and free the handle.

    One memcpy per column family; the string heap comes out as a single
    Python bytes object the views slice lazily. ``tables`` interned-blob
    families come out as lists of bytes.
    """
    count = lib.batch_count(handle)
    i64 = np.ctypeslib.as_array(
        lib.batch_i64(handle), shape=(count * ni64,)
    ).reshape(count, ni64).copy() if ni64 and count else np.zeros(
        (count, ni64), np.int64
    )
    i32 = np.ctypeslib.as_array(
        lib.batch_i32(handle), shape=(count * ni32,)
    ).reshape(count, ni32).copy() if ni32 and count else np.zeros(
        (count, ni32), np.int32
    )
    u8 = np.ctypeslib.as_array(
        lib.batch_u8(handle), shape=(count * nu8,)
    ).reshape(count, nu8).copy() if nu8 and count else np.zeros(
        (count, nu8), np.uint8
    )
    stroff = np.ctypeslib.as_array(
        lib.batch_str(handle), shape=(count * nstr * 2,)
    ).reshape(count, nstr, 2).copy() if count else np.zeros(
        (0, nstr, 2), np.int64
    )
    hlen = ctypes.c_long()
    hptr = lib.batch_heap(handle, ctypes.byref(hlen))
    heap = ctypes.string_at(hptr, hlen.value)
    tbls: List[List[bytes]] = []
    for family in range(tables):
        tcount = ctypes.c_long()
        toff = lib.batch_table(handle, family, ctypes.byref(tcount))
        blobs = []
        for t in range(tcount.value):
            off, ln = toff[2 * t], toff[2 * t + 1]
            blobs.append(heap[off : off + ln])
        tbls.append(blobs)
    rv = (lib.batch_rv(handle) or b"").decode()
    lib.ingest_free(handle)
    return count, i64, i32, u8, stroff, heap, rv, tbls


@functools.lru_cache(maxsize=4096)
def _parse_tolerations(blob: bytes) -> Tuple[Toleration, ...]:
    out = []
    for rec in blob.decode().split(_REC):
        if not rec:
            continue
        key, value, operator, effect = rec.split(_UNIT)
        out.append(
            Toleration(key=key, value=value, operator=operator, effect=effect)
        )
    return tuple(out)


def _parse_kv(blob: bytes) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for rec in blob.decode().split(_REC):
        if rec:
            k, _, v = rec.partition(_UNIT)
            out[k] = v
    return out


@functools.lru_cache(maxsize=4096)
def _parse_spread(blob: bytes) -> Tuple:
    """Spread blob (ingest.cc extract_topology_spread) -> the exact
    canonical tuples io/kube.py ``decode_topology_spread`` produces:
    (topology_key, max_skew, selector requirements), entries
    sorted+deduped. Round-5 format: requirements joined by TERM_SEP,
    each ``key VAL_SEP op VAL_SEP v1 VAL_SEP v2 ...`` (no values for
    Exists/DoesNotExist). The engine emits source order;
    canonicalization lives here (same contract as the node-affinity
    blob)."""
    if not blob:
        return ()
    out = []
    for rec in blob.decode().split(_REC):
        topo, skew, reqs_field = rec.split(_UNIT)
        reqs = []
        for req in reqs_field.split(_TERM):
            key, op, *values = req.split(_VAL)
            if op in ("Exists", "DoesNotExist"):
                vals: Tuple[str, ...] = ()
            else:
                vals = tuple(sorted(set(values)))
            reqs.append((key, op, vals))
        out.append((topo, int(skew), tuple(sorted(set(reqs)))))
    return tuple(sorted(set(out)))


@functools.lru_cache(maxsize=4096)
def _parse_affinity_terms(blob: bytes) -> Tuple:
    """Pod-affinity term blob (ingest.cc term_selector_blob) -> proto
    terms ``((namespaces | None, selector), ...)`` in source order,
    each selector canonicalized (sorted, deduped). ``None`` namespaces
    mean the pod's own namespace — resolved per pod by
    ``_resolve_terms`` (the blob is interned ACROSS pods of different
    namespaces, so resolution cannot happen here). Format: terms joined
    by TERM_SEP; term records joined by REC_SEP — record 0 is the
    namespaces list joined by VAL_SEP (empty = own namespace), the rest
    are ``key UNIT_SEP op UNIT_SEP values-joined-by-VAL_SEP``."""
    if not blob:
        return ()
    out = []
    for term_rec in blob.decode().split(_TERM):
        recs = term_rec.split(_REC)
        ns_rec = recs[0]
        nss = tuple(sorted(set(ns_rec.split(_VAL)))) if ns_rec else None
        reqs = []
        for rec in recs[1:]:
            key, op, values = rec.split(_UNIT)
            if op in ("Exists", "DoesNotExist"):
                vals: Tuple[str, ...] = ()
            else:
                vals = tuple(sorted(set(values.split(_VAL))))
            reqs.append((key, op, vals))
        out.append((nss, tuple(sorted(set(reqs)))))
    return tuple(out)


def _resolve_terms(proto: Tuple, ns: str, drop_nothing: bool) -> Tuple:
    """Finalize proto terms for one pod namespace: own-namespace scopes
    resolve to ``(ns,)``; anti-affinity families drop never-matching
    selectors exactly (they constrain nothing — io/kube.py lockstep)
    while positive families keep them (no resident can match -> the
    carrier is exactly unplaceable)."""
    from k8s_spot_rescheduler_tpu.predicates.selectors import (
        selector_matches_nothing,
    )

    out = []
    for nss, sel in proto:
        if drop_nothing and selector_matches_nothing(sel):
            continue
        out.append((nss if nss is not None else (ns,), sel))
    return tuple(sorted(set(out)))


@functools.lru_cache(maxsize=4096)
def _parse_node_affinity(blob: bytes) -> Tuple:
    """Node-affinity blob (ingest.cc extract_node_affinity) -> the exact
    canonical tuples io/kube.py ``decode_node_affinity`` produces: terms
    and their expressions sorted, In/NotIn value lists sorted+deduped.
    The engine emits source order; canonicalization lives here so the two
    languages share no sort-order contract."""
    if not blob:
        return ()
    terms = []
    for term_rec in blob.decode().split(_TERM):
        exprs = []
        for rec in term_rec.split(_REC):
            key, op, values = rec.split(_UNIT)
            if op in ("Exists", "DoesNotExist"):
                vals: Tuple[str, ...] = ()
            elif op in ("Gt", "Lt"):
                vals = (values,)
            else:  # In / NotIn
                vals = tuple(sorted(set(values.split(_VAL))))
            exprs.append((key, op, vals))
        terms.append(tuple(sorted(exprs)))
    return tuple(sorted(set(terms)))


@functools.lru_cache(maxsize=1024)
def _parse_taints(blob: bytes) -> Tuple[Taint, ...]:
    out = []
    for rec in blob.decode().split(_REC):
        if not rec:
            continue
        key, value, effect = rec.split(_UNIT)
        out.append(Taint(key, value, effect))
    return tuple(out)


class PodBatch:
    """Columnar pods from one LIST response, with lazy row views.

    Interned tables (node names, namespaces, toleration sets, label sets)
    decode once per distinct value; rows carry int32 ids into them.
    """

    def __init__(self, count, i64, i32, u8, stroff, heap, rv, tables):
        self.count = count
        self.i64, self.i32, self.u8 = i64, i32, u8
        self.stroff, self.heap = stroff, heap
        self.resource_version = rv
        self.node_names = [b.decode() for b in tables[TBL_NODE]]
        self.namespaces = [b.decode() for b in tables[TBL_NS]]
        self.tol_sets = [_parse_tolerations(b) for b in tables[TBL_TOLS]]
        self.label_blobs = tables[TBL_LABELS]
        self._label_sets: List[Optional[Dict[str, str]]] = [None] * len(
            self.label_blobs
        )
        self.selector_sets = [_parse_kv(b) for b in tables[TBL_NODESEL]]
        # proto affinity terms (own-ns unresolved); resolved per
        # (set_id, namespace) on demand below
        self.match_protos = [_parse_affinity_terms(b) for b in tables[TBL_AAFF]]
        self.paff_protos = [_parse_affinity_terms(b) for b in tables[TBL_PAFF]]
        self.zaff_protos = [_parse_affinity_terms(b) for b in tables[TBL_ZAFF]]
        self.pzaff_protos = [
            _parse_affinity_terms(b) for b in tables[TBL_PZAFF]
        ]
        self._resolved: Dict[Tuple[int, int, str], Tuple] = {}
        self.pvc_lists = [
            tuple(b.decode().split(_REC)) if b else () for b in tables[TBL_PVC]
        ]
        self.naff_sets = [_parse_node_affinity(b) for b in tables[TBL_NAFF]]
        self.spread_sets = [_parse_spread(b) for b in tables[TBL_SPREAD]]

    def _terms(self, family: int, protos, set_id: int, ns: str,
               drop_nothing: bool) -> Tuple:
        key = (family, set_id, ns)
        cached = self._resolved.get(key)
        if cached is None:
            cached = self._resolved[key] = _resolve_terms(
                protos[set_id], ns, drop_nothing
            )
        return cached

    def match_terms(self, set_id: int, ns: str) -> Tuple:
        return self._terms(0, self.match_protos, set_id, ns, True)

    def zaff_terms(self, set_id: int, ns: str) -> Tuple:
        return self._terms(1, self.zaff_protos, set_id, ns, True)

    def paff_terms(self, set_id: int, ns: str) -> Tuple:
        return self._terms(2, self.paff_protos, set_id, ns, False)

    def pzaff_terms(self, set_id: int, ns: str) -> Tuple:
        return self._terms(3, self.pzaff_protos, set_id, ns, False)

    def pvc_list(self, set_id: int) -> tuple:
        return self.pvc_lists[set_id]

    def any_pvc_resolvable(self) -> bool:
        """Vectorized ``any(view.pvc_resolvable)`` over the batch — the
        same predicate PodView evaluates (F_PVC set, non-empty claim
        list, no F_REQAFF), without materializing 50k lazy views on the
        polling hot path (advisor r3). The per-list emptiness check runs
        over the small interned table, not per pod."""
        import numpy as np

        flags = self.u8[: self.count, 0]
        pvc = (flags & F_PVC) != 0
        if not pvc.any():
            return False
        nonempty = np.fromiter(
            (bool(l) for l in self.pvc_lists), bool, count=len(self.pvc_lists)
        )
        return bool(
            (
                pvc
                & ((flags & F_REQAFF) == 0)
                & nonempty[self.i32[: self.count, P_PVCID]]
            ).any()
        )

    def label_set(self, set_id: int) -> Dict[str, str]:
        cached = self._label_sets[set_id]
        if cached is None:
            cached = self._label_sets[set_id] = _parse_kv(
                self.label_blobs[set_id]
            )
        return cached

    def selector_set(self, set_id: int) -> Dict[str, str]:
        return self.selector_sets[set_id]

    def _str(self, i: int, col: int) -> bytes:
        off, ln = self.stroff[i, col]
        return self.heap[off : off + ln]

    def view(self, i: int) -> "PodView":
        return PodView(self, i)

    def views(self) -> List["PodView"]:
        return [PodView(self, i) for i in range(self.count)]


class PodView:
    """Duck-typed ``PodSpec`` over a batch row; dicts materialize lazily.

    Covers every attribute the framework reads off a pod: the columnar
    store (requests/priority/flags/tolerations/labels), the evictability
    filter, the node-map builder, the actuator (name/namespace/uid), and
    the unschedulable gate (phase/node_name).
    """

    __slots__ = ("_b", "_i", "_requests", "_labels")

    def __init__(self, batch: PodBatch, i: int):
        self._b = batch
        self._i = i
        self._requests: Optional[Dict[str, int]] = None
        self._labels: Optional[Dict[str, str]] = None

    @property
    def name(self) -> str:
        return self._b._str(self._i, PS_NAME).decode()

    @property
    def namespace(self) -> str:
        return self._b.namespaces[self._b.i32[self._i, P_NSID]]

    @property
    def node_name(self) -> str:
        return self._b.node_names[self._b.i32[self._i, P_NODEID]]

    @property
    def uid(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def meta_uid(self) -> str:
        """metadata.uid — the watch-store key (PodSpec has no analog)."""
        return self._b._str(self._i, PS_UID).decode()

    @property
    def requests(self) -> Dict[str, int]:
        if self._requests is None:
            row = self._b.i64[self._i]
            self._requests = {}
            if row[P_CPU]:
                self._requests["cpu"] = int(row[P_CPU])
            if row[P_MEM]:
                self._requests["memory"] = int(row[P_MEM])
            if row[P_EPH]:
                self._requests["ephemeral-storage"] = int(row[P_EPH])
        return self._requests

    @property
    def priority(self) -> int:
        return int(self._b.i32[self._i, P_PRIO])

    @property
    def labels(self) -> Dict[str, str]:
        if self._labels is None:
            self._labels = self._b.label_set(
                int(self._b.i32[self._i, P_LABELSID])
            )
        return self._labels

    @property
    def annotations(self) -> Dict[str, str]:
        # only the mirror annotation is ever read; synthesize it from flags
        if self._b.u8[self._i, 0] & F_MIRROR:
            return {MIRROR_POD_ANNOTATION: "true"}
        return {}

    @property
    def owner_refs(self) -> List[OwnerRef]:
        flags = self._b.u8[self._i, 0]
        if flags & F_REPLICATED:
            kind = "DaemonSet" if flags & F_DAEMONSET else "ReplicaSet"
            return [OwnerRef(kind=kind, name="", controller=True)]
        return []

    @property
    def tolerations(self) -> Tuple[Toleration, ...]:
        return self._b.tol_sets[self._b.i32[self._i, P_TOLID]]

    @property
    def anti_affinity_group(self) -> str:
        return ""  # the simplified group field is synthetic-only

    @property
    def anti_affinity_match(self) -> Tuple:
        return self._b.match_terms(
            int(self._b.i32[self._i, P_AAFFID]), self.namespace
        )

    @property
    def pod_affinity_match(self) -> Tuple:
        return self._b.paff_terms(
            int(self._b.i32[self._i, P_PAFFID]), self.namespace
        )

    @property
    def anti_affinity_zone_match(self) -> Tuple:
        return self._b.zaff_terms(
            int(self._b.i32[self._i, P_ZAFFID]), self.namespace
        )

    @property
    def pvc_names(self) -> tuple:
        return self._b.pvc_list(int(self._b.i32[self._i, P_PVCID]))

    @property
    def pvc_resolvable(self) -> bool:
        # decode_pod lockstep: claims present with a clean name list and
        # no other unmodeled constraint (F_REQAFF covers affinity shapes
        # AND hard spread constraints on the native side)
        flags = self._b.u8[self._i, 0]
        return bool(
            (flags & F_PVC)
            and self.pvc_names
            and not (flags & F_REQAFF)
        )

    @property
    def spread_constraints(self) -> tuple:
        return self._b.spread_sets[int(self._b.i32[self._i, P_SPREADID])]

    @property
    def pod_affinity_zone_match(self) -> Tuple:
        return self._b.pzaff_terms(
            int(self._b.i32[self._i, P_PZAFFID]), self.namespace
        )

    @property
    def node_selector(self) -> Dict[str, str]:
        return self._b.selector_set(int(self._b.i32[self._i, P_SELID]))

    @property
    def node_affinity(self) -> tuple:
        return self._b.naff_sets[int(self._b.i32[self._i, P_NAFFID])]

    @property
    def unmodeled_constraints(self) -> bool:
        return bool(self._b.u8[self._i, 0] & (F_PVC | F_REQAFF))

    @property
    def phase(self) -> str:
        flags = self._b.u8[self._i, 0]
        if flags & F_PENDING:
            return "Pending"
        if flags & F_TERMINAL:
            return "Succeeded"
        return "Running"

    def is_mirror(self) -> bool:
        return bool(self._b.u8[self._i, 0] & F_MIRROR)

    def is_daemonset(self) -> bool:
        return bool(self._b.u8[self._i, 0] & F_DAEMONSET)

    def controller_ref(self) -> Optional[OwnerRef]:
        refs = self.owner_refs
        return refs[0] if refs else None

    def to_pod_spec(self) -> PodSpec:
        """Full materialization (tests / fallback interop)."""
        return PodSpec(
            name=self.name,
            namespace=self.namespace,
            node_name=self.node_name,
            requests=dict(self.requests),
            priority=self.priority,
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            owner_refs=list(self.owner_refs),
            tolerations=list(self.tolerations),
            phase=self.phase,
            node_selector=dict(self.node_selector),
            anti_affinity_match=self.anti_affinity_match,
            anti_affinity_zone_match=self.anti_affinity_zone_match,
            pvc_names=self.pvc_names,
            pvc_resolvable=self.pvc_resolvable,
            pod_affinity_match=self.pod_affinity_match,
            pod_affinity_zone_match=self.pod_affinity_zone_match,
            node_affinity=self.node_affinity,
            spread_constraints=self.spread_constraints,
            unmodeled_constraints=self.unmodeled_constraints,
        )

    def __repr__(self) -> str:
        return f"PodView({self.uid} on {self.node_name!r})"


class NodeBatch:
    def __init__(self, count, i64, i32, u8, stroff, heap, rv, tables):
        self.count = count
        self.i64, self.u8 = i64, u8
        self.stroff, self.heap = stroff, heap
        self.resource_version = rv

    def _str(self, i: int, col: int) -> bytes:
        off, ln = self.stroff[i, col]
        return self.heap[off : off + ln]

    def views(self) -> List["NodeView"]:
        return [NodeView(self, i) for i in range(self.count)]


class NodeView:
    """Duck-typed ``NodeSpec`` over a batch row."""

    __slots__ = ("_b", "_i", "_labels", "_alloc", "_taints")

    def __init__(self, batch: NodeBatch, i: int):
        self._b = batch
        self._i = i
        self._labels: Optional[Dict[str, str]] = None
        self._alloc: Optional[Dict[str, int]] = None
        self._taints: Optional[List[Taint]] = None

    @property
    def name(self) -> str:
        return self._b._str(self._i, NS_NAME).decode()

    @property
    def meta_uid(self) -> str:
        return self._b._str(self._i, NS_UID).decode()

    @property
    def labels(self) -> Dict[str, str]:
        if self._labels is None:
            self._labels = _parse_kv(self._b._str(self._i, NS_LABELS))
        return self._labels

    @property
    def allocatable(self) -> Dict[str, int]:
        if self._alloc is None:
            row = self._b.i64[self._i]
            self._alloc = {}
            if row[N_CPU]:
                self._alloc["cpu"] = int(row[N_CPU])
            if row[N_MEM]:
                self._alloc["memory"] = int(row[N_MEM])
            if row[N_EPH]:
                self._alloc["ephemeral-storage"] = int(row[N_EPH])
            if self._b.u8[self._i, N_HASPODS]:
                self._alloc["pods"] = int(row[N_PODS])
        return self._alloc

    @property
    def taints(self) -> List[Taint]:
        if self._taints is None:
            self._taints = list(_parse_taints(self._b._str(self._i, NS_TAINTS)))
        return self._taints

    # the actuator mutates taints via the apiserver, not on the view;
    # watch MODIFIED events deliver fresh views
    @taints.setter
    def taints(self, value) -> None:
        self._taints = list(value)

    @property
    def ready(self) -> bool:
        return bool(self._b.u8[self._i, N_READY])

    @property
    def unschedulable(self) -> bool:
        return bool(self._b.u8[self._i, N_UNSCHED])

    def allocatable_cpu(self) -> int:
        return int(self.allocatable.get("cpu", 0))

    def to_node_spec(self) -> NodeSpec:
        return NodeSpec(
            name=self.name,
            labels=dict(self.labels),
            allocatable=dict(self.allocatable),
            taints=list(self.taints),
            ready=self.ready,
            unschedulable=self.unschedulable,
        )

    def __repr__(self) -> str:
        return f"NodeView({self.name!r})"


def parse_pod_list(data: bytes) -> Optional[PodBatch]:
    """Parse a PodList JSON body natively; None if the engine is absent
    or the body doesn't parse (caller falls back to Python)."""
    lib = _lib()
    if lib is None:
        return None
    handle = lib.ingest_pods(data, len(data))
    if not handle:
        return None
    return PodBatch(*_copy_batch(lib, handle, 3, 13, 1, 2, tables=12))


def parse_node_list(data: bytes) -> Optional[NodeBatch]:
    lib = _lib()
    if lib is None:
        return None
    handle = lib.ingest_nodes(data, len(data))
    if not handle:
        return None
    return NodeBatch(*_copy_batch(lib, handle, 4, 0, 3, 4))
