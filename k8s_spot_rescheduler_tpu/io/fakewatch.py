"""Scripted in-memory apiserver for the watch protocol — no HTTP, no
threads, no real time.

``tests/test_watch.py`` exercises the watch stack over a real streaming
HTTP stub, which is the right fidelity for protocol tests but the wrong
substrate for a *soak*: hundreds of ticks with injected stalls must run
on a virtual clock, and a virtual clock cannot coexist with watcher
threads blocked in real socket reads. ``ScriptedWatchSource`` provides
the exact surface the watch stack consumes — ``_request`` for LISTs,
``_stream`` for watch streams, plus the full ``ClusterClient`` read and
write verbs for the freshness gate's direct-LIST bypass and the drain
path — over plain dicts of raw API objects, so a soak drives
``Watcher.step()`` synchronously and deterministically (the seeded soak
in ``bench.py --watch-soak`` and tests/test_freshness.py).

Chaos composes the same way as production: wrap this source in a
``ChaosClusterClient`` (whose ``_stream`` hook injects drops, scripted
410s, and open-but-silent stalls) and hand THAT to
``WatchingKubeClusterClient``.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

from k8s_spot_rescheduler_tpu.io.cluster import EvictionError
from k8s_spot_rescheduler_tpu.io.kube import decode_node, decode_pdb, decode_pod
from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeSpec,
    PDBSpec,
    PodSpec,
    Taint,
)

RESOURCES = {
    "/api/v1/nodes": "nodes",
    "/api/v1/pods": "pods",
    "/apis/policy/v1/poddisruptionbudgets": "pdbs",
}


def raw_node(name: str, role: str, *, cpu_millis: int = 4000,
             ready: bool = True) -> dict:
    return {
        "metadata": {"name": name, "uid": f"uid-{name}",
                     "labels": {"kubernetes.io/role": role},
                     "resourceVersion": "1"},
        "spec": {},
        "status": {
            "allocatable": {"cpu": f"{cpu_millis}m", "memory": "8Gi",
                            "pods": "110"},
            "conditions": [
                {"type": "Ready", "status": "True" if ready else "False"}
            ],
        },
    }


def raw_pod(name: str, node: str, *, cpu_millis: int = 100,
            phase: str = "Running") -> dict:
    return {
        "metadata": {
            "name": name, "namespace": "default", "uid": f"uid-{name}",
            "labels": {"app": name}, "resourceVersion": "1",
            "ownerReferences": [
                {"kind": "ReplicaSet", "name": f"{name}-rs",
                 "controller": True}
            ],
        },
        "spec": {
            "nodeName": node,
            "containers": [
                {"resources": {"requests": {"cpu": f"{cpu_millis}m",
                                            "memory": "64Mi"}}}
            ],
        },
        "status": {"phase": phase},
    }


class ScriptedWatchSource:
    """Raw-dict apiserver double serving LIST + WATCH + the ClusterClient
    verbs, fully synchronous. Watch streams drain the currently queued
    events and then end (a server-side close); nothing blocks."""

    def __init__(self) -> None:
        self.objects: Dict[str, Dict[str, dict]] = {
            "nodes": {}, "pods": {}, "pdbs": {},
        }
        self.rv = {"nodes": 10, "pods": 10, "pdbs": 10}
        self.queues: Dict[str, collections.deque] = {
            r: collections.deque() for r in self.rv
        }
        self.list_count = {r: 0 for r in self.rv}
        self.stream_count = {r: 0 for r in self.rv}
        self.watch_params: List[tuple] = []  # (resource, rv or None)
        # ClusterClient read verbs served straight off the dicts (the
        # freshness gate's direct-LIST bypass path) — counted separately
        # from the watch stack's _request LISTs
        self.direct_reads = 0
        self.evictions: List[str] = []
        self.events: List[tuple] = []
        # the watch path skips the native LIST decoder (raw dicts here
        # never pass through real HTTP bodies)
        self.use_native_ingest = False

    # --- state mutation (the "cluster" changing) ---

    def push(self, resource: str, etype: str, obj: dict) -> None:
        """Apply a change and queue its watch event (like a real
        apiserver: state and stream advance together)."""
        self.rv[resource] += 1
        obj = dict(obj)
        obj["metadata"] = dict(
            obj["metadata"], resourceVersion=str(self.rv[resource])
        )
        uid = obj["metadata"]["uid"]
        if etype == "DELETED":
            self.objects[resource].pop(uid, None)
        else:
            self.objects[resource][uid] = obj
        self.queues[resource].append({"type": etype, "object": obj})

    def bookmark(self, resource: str) -> None:
        self.rv[resource] += 1
        self.queues[resource].append({
            "type": "BOOKMARK",
            "object": {"metadata": {
                "resourceVersion": str(self.rv[resource])
            }},
        })

    # --- watch-stack plumbing (what Watcher consumes) ---

    def _request(self, method: str, path: str, body=None, **kwargs):
        base = path.split("?", 1)[0]
        resource = RESOURCES.get(base)
        if method == "GET" and resource is not None:
            self.list_count[resource] += 1
            self.rv[resource] += 1
            return {
                "metadata": {"resourceVersion": str(self.rv[resource])},
                "items": list(self.objects[resource].values()),
            }
        raise ValueError(f"scripted source: unsupported {method} {path}")

    def _stream(self, path: str, read_timeout: float = 330.0):
        base, _, query = path.partition("?")
        resource = RESOURCES[base]
        self.stream_count[resource] += 1
        rv = None
        for part in query.split("&"):
            if part.startswith("resourceVersion="):
                rv = part.split("=", 1)[1]
        self.watch_params.append((resource, rv))
        q = self.queues[resource]
        while q:
            yield q.popleft()
        # queue drained: the server closes the stream (timeoutSeconds)

    def list_volume_snapshots(self):
        return {}, {}

    # --- ClusterClient read verbs (the direct-LIST bypass path) ---

    def refresh(self) -> None:
        pass

    def _nodes(self) -> List[NodeSpec]:
        return [decode_node(o) for o in self.objects["nodes"].values()]

    def _pods(self) -> List[PodSpec]:
        return [decode_pod(o) for o in self.objects["pods"].values()]

    def list_ready_nodes(self) -> List[NodeSpec]:
        self.direct_reads += 1
        return [n for n in self._nodes() if n.ready]

    def list_unready_nodes(self) -> List[NodeSpec]:
        self.direct_reads += 1
        return [n for n in self._nodes() if not n.ready]

    def list_pods_on_node(self, node_name: str) -> List[PodSpec]:
        self.direct_reads += 1
        return [p for p in self._pods() if p.node_name == node_name]

    def list_unschedulable_pods(self) -> List[PodSpec]:
        self.direct_reads += 1
        return [
            p for p in self._pods()
            if not p.node_name and p.phase == "Pending"
        ]

    def list_pdbs(self) -> List[PDBSpec]:
        self.direct_reads += 1
        return [decode_pdb(o) for o in self.objects["pdbs"].values()]

    def get_pod(self, namespace: str, name: str) -> Optional[PodSpec]:
        for obj in self.objects["pods"].values():
            meta = obj["metadata"]
            if meta["name"] == name and meta["namespace"] == namespace:
                return decode_pod(obj)
        return None

    # --- write verbs (the drain path; state changes flow back into the
    # watch streams exactly like a real apiserver) ---

    def evict_pod(self, pod: PodSpec, grace_seconds: int) -> None:
        for obj in list(self.objects["pods"].values()):
            if (
                obj["metadata"]["name"] == pod.name
                and obj["metadata"]["namespace"] == pod.namespace
            ):
                self.evictions.append(pod.name)
                self.push("pods", "DELETED", obj)
                return
        raise EvictionError(f"evict {pod.uid}: not found")

    def _patch_taints(self, node_name: str, mutate) -> None:
        for obj in self.objects["nodes"].values():
            if obj["metadata"]["name"] == node_name:
                taints = list(obj["spec"].get("taints", []) or [])
                obj = dict(obj, spec=dict(obj["spec"], taints=mutate(taints)))
                self.push("nodes", "MODIFIED", obj)
                return
        raise KeyError(node_name)

    def add_taint(self, node_name: str, taint: Taint) -> None:
        entry = {"key": taint.key, "value": taint.value,
                 "effect": taint.effect}
        self._patch_taints(
            node_name,
            lambda ts: [t for t in ts if t.get("key") != taint.key] + [entry],
        )

    def remove_taint(self, node_name: str, taint_key: str) -> None:
        self._patch_taints(
            node_name,
            lambda ts: [t for t in ts if t.get("key") != taint_key],
        )

    # --- event sink ---

    def event(self, kind, name, event_type, reason, message) -> None:
        self.events.append((kind, name, event_type, reason, message))
