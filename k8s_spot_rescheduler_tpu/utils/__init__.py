"""Utility layer: config, quantities, labels, logging, clocks."""

from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils.labels import (
    matches_label,
    validate_label,
)
from k8s_spot_rescheduler_tpu.utils.quantity import (
    parse_cpu_millis,
    parse_memory_bytes,
    parse_quantity,
)
from k8s_spot_rescheduler_tpu.utils.clock import Clock, FakeClock, RealClock

__all__ = [
    "ReschedulerConfig",
    "matches_label",
    "validate_label",
    "parse_cpu_millis",
    "parse_memory_bytes",
    "parse_quantity",
    "Clock",
    "FakeClock",
    "RealClock",
]
