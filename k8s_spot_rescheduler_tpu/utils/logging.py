"""Leveled logging in the style of the reference's glog usage.

The reference logs at -v=2 (state transitions, rescheduler.go:168, 266,
278), -v=3 (tick start/finish, 183, 289) and -v=4 (per-(pod,node) predicate
failures, 348). ``vlog(level, ...)`` reproduces that: messages are emitted
when the configured verbosity is >= level.
"""

from __future__ import annotations

import logging
import sys

_logger = logging.getLogger("spot_rescheduler_tpu")
_verbosity = 0


def setup(verbosity: int = 0, stream=None) -> None:
    """Configure stderr logging (the reference forces logtostderr=true,
    rescheduler.go:93-96)."""
    global _verbosity
    _verbosity = verbosity
    if not _logger.handlers:
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(message)s")
        )
        _logger.addHandler(handler)
    _logger.setLevel(logging.DEBUG)


def verbosity() -> int:
    return _verbosity


def vlog(level: int, msg: str, *args) -> None:
    if _verbosity >= level:
        _logger.info(msg, *args)


def info(msg: str, *args) -> None:
    _logger.info(msg, *args)


def warning(msg: str, *args) -> None:
    _logger.warning(msg, *args)


def error(msg: str, *args) -> None:
    _logger.error(msg, *args)
