"""Kubernetes resource-quantity parsing.

The reference reads quantities through k8s ``resource.Quantity`` and plans
on CPU MilliValues (reference nodes/nodes.go:149-165). This module is the
framework's equivalent: parse the canonical k8s quantity grammar
(plain/decimal numbers, binary suffixes Ki..Ei, decimal suffixes k..E, and
the milli suffix ``m``) into exact integers.
"""

from __future__ import annotations

from fractions import Fraction

_BINARY = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 1000),
    "": Fraction(1),
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}


def parse_quantity(s: str | int | float) -> Fraction:
    """Parse a k8s quantity string into an exact Fraction of base units."""
    if isinstance(s, (int, float)):
        return Fraction(s)
    s = s.strip()
    if not s:
        raise ValueError("empty quantity")
    for suffix, mult in _BINARY.items():
        if s.endswith(suffix):
            return Fraction(s[: -len(suffix)]) * mult
    # decimal suffixes: longest first not needed (all 1 char); handle exponent
    # forms like 1e3 by letting Fraction parse them via float-free path
    last = s[-1]
    if last in _DECIMAL and not last.isdigit():
        return Fraction(s[:-1]) * _DECIMAL[last]
    if "e" in s or "E" in s:
        mantissa, _, exp = s.replace("E", "e").partition("e")
        return Fraction(mantissa) * Fraction(10) ** int(exp)
    return Fraction(s)


def parse_cpu_millis(s: str | int | float) -> int:
    """CPU quantity → integer millicores (the reference's MilliValue,
    nodes/nodes.go:149-165). Rounds up like k8s ``MilliValue`` does for
    sub-milli values."""
    q = parse_quantity(s) * 1000
    return int(-(-q.numerator // q.denominator))  # ceil


def parse_memory_bytes(s: str | int | float) -> int:
    """Memory quantity → integer bytes (ceil)."""
    q = parse_quantity(s)
    return int(-(-q.numerator // q.denominator))
