"""Per-tick tracing: span trees, phase timers, profiler hooks.

The reference's only observability into its hot path is glog verbosity
(SURVEY.md §5.1); here every housekeeping tick carries an in-process
*trace* — a tick-scoped trace ID plus a tree of nested ``span`` records
(monotonic start/duration, typed attributes) — threaded through the
control loop, the kube read path, the actuator and the planner, and
*across the service wire*: the agent ships its trace ID with each plan
request (``X-Trace-Id`` header + a wire frame, service/wire.py v2) and
the planner service returns its own spans (admit, decode, queue-wait,
batch assembly, solve, encode) compactly in the reply, which the agent
grafts into the tick's tree. One tree answers "queue or solve or wire?"
for any given slow tick. Completed traces feed the flight recorder
(loop/flight.py); the last tree is inspectable via ``/debug/trace``.

Tracing is always-on-cheap: O(spans) host work per tick (dict/list
appends + ``perf_counter`` reads), zero device syncs, and a hard
``MAX_SPANS`` cap so a pathological tick cannot grow a trace without
bound (drops are counted on the trace). ``trace_enabled`` (config)
turns the whole layer off.

Phases of the pipelined tick (loop/controller.py): ``observe`` (cluster
state + PDBs), ``plan-dispatch`` (host pack + delta-upload + async solve
dispatch), ``observe-metrics`` (per-node metrics — host work that runs
WHILE the device solve is in flight), ``plan-fetch`` (the blocking
selection fetch + report build), ``actuate``. The aggregate ``plan``
series (dispatch + fetch, excluding the overlapped window) is kept for
dashboard continuity; ``plan-fetch`` minus the true device time is the
residual the overlap did not hide.

Span-name registry
------------------
Every span name emitted anywhere in the package MUST be declared in
``SPAN_NAMES`` below and vice versa — enforced by the ``trace-contract``
static-analysis pass (tools/analysis/passes/contracts.py), so dashboards
and the flight-recorder schema cannot silently drift. Emit spans only
through this module's ``phase(...)`` / ``span(...)`` / ``make_span(...)``
helpers with a literal name (that is what the pass scans).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.utils import logging as log

# name -> one-line meaning. The single source of truth for every span
# emitted anywhere (docs/OBSERVABILITY.md renders this table; the
# trace-contract pass enforces both directions).
SPAN_NAMES: Dict[str, str] = {
    # control-loop tick phases (loop/controller.py, via phase())
    "observe": "cluster state + PDB listing (object or columnar path)",
    "plan": "aggregate plan phase (dispatch + fetch, overlap excluded)",
    "plan-dispatch": "host pack + delta upload + async solve dispatch",
    "observe-metrics": "per-node metrics pass (overlaps the device solve)",
    "plan-fetch": "blocking selection fetch + PlanReport build",
    "actuate": "drain actuation (taint, evict, verify, untaint)",
    # kube API read path (io/kube.py retry loop)
    "kube.get": "one kube API read incl. transient retries (attempts attr)",
    # actuator rounds (actuator/drain.py)
    "drain.evict": "one parallel eviction round over the remaining pods",
    "drain.verify": "one verification poll round over the drained pods",
    # planner internals (planner/solver_planner.py, service/agent.py)
    "plan.pack": "host pack of the observation into problem tensors",
    "plan.delta-upload": "device-resident cache update (delta or repack)",
    "plan.solve": "the solve the tick actually waited on (fetch/oracle)",
    "plan.schedule": "drain-to-exhaustion schedule cut: one fetch, H steps",
    # agent <-> service wire (service/agent.py)
    "wire.request": "full service round trip; server spans graft under it",
    "wire.transfer": "wire residual: round trip minus server-side spans",
    "wire.connect": "TCP connect for a fresh pooled socket (absent on reuse)",
    "wire.failover": "one FAILED endpoint attempt before failing over",
    # service-side spans, returned compactly in the PlanReply and
    # grafted by the agent (service/server.py)
    "service.admit": "inflight admission + request body read",
    "service.decode": "wire decode + contract checks of the request",
    "service.queue-wait": "time in the tenant queue before batch pop",
    "service.batch": "bucket padding + tenant stacking of the batch",
    "service.solve": "the batched device (or host-oracle) solve",
    "service.encode": "wire encode of the reply",
}

# hard per-trace span cap: a pathological tick (huge drain fan-out,
# retry storm) must bound its own observability cost; drops are counted
MAX_SPANS = 512


class Span:
    """One timed region. ``t0_ms`` is the offset from its scope's start
    (trace start for loop-side spans; request receipt / enqueue for
    server-returned spans — offsets are scope-local, not global)."""

    __slots__ = ("name", "t0_ms", "dur_ms", "attrs", "children")

    def __init__(self, name: str, t0_ms: float = 0.0, dur_ms: float = 0.0,
                 attrs: Optional[dict] = None):
        self.name = name
        self.t0_ms = t0_ms
        self.dur_ms = dur_ms
        self.attrs = attrs if attrs is not None else {}
        self.children: List[Span] = []

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "t0_ms": round(self.t0_ms, 3),
            "dur_ms": round(self.dur_ms, 3),
        }
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.children:
            out["spans"] = [c.to_dict() for c in self.children]
        return out


class Trace:
    """One tick's span tree. Single-threaded by design: spans open and
    close on the owning (loop) thread; worker threads hand back raw
    timestamps and the owner grafts them (service/agent.py)."""

    def __init__(self, trace_id: str = ""):
        self.trace_id = trace_id or new_trace_id()
        self.wall = time.time()
        self.attrs: Dict[str, object] = {}
        self.spans: List[Span] = []
        self.dropped = 0
        self._t0 = time.perf_counter()
        self._stack: List[Span] = []
        self._n = 0

    # ------------------------------------------------------------------

    def _admit(self) -> bool:
        if self._n >= MAX_SPANS:
            self.dropped += 1
            return False
        self._n += 1
        return True

    def _attach(self, sp: Span) -> None:
        if self._stack:
            self._stack[-1].children.append(sp)
        else:
            self.spans.append(sp)

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """One nested timed region; yields the Span (or None past the
        cap). A body that raises still records the span, with an
        ``error: true`` attribute, and re-raises."""
        if not self._admit():
            yield None
            return
        start = time.perf_counter()
        sp = Span(name, (start - self._t0) * 1e3, attrs=attrs or None)
        self._stack.append(sp)
        try:
            yield sp
        except BaseException:
            sp.attrs["error"] = True
            raise
        finally:
            sp.dur_ms = (time.perf_counter() - start) * 1e3
            self._stack.pop()
            self._attach(sp)

    def graft(
        self,
        parent: Tuple[str, float, float],
        children: Iterable[Tuple[str, float, float]] = (),
        attrs: Optional[dict] = None,
    ) -> Optional[Span]:
        """Attach an already-measured span (plus flat children) at the
        current nesting level — how the agent folds the server-returned
        ``(name, t0_ms, dur_ms)`` tuples into the tick tree."""
        if not self._admit():
            return None
        sp = Span(parent[0], float(parent[1]), float(parent[2]),
                  attrs=dict(attrs) if attrs else None)
        for child in children:
            if not self._admit():
                break
            sp.children.append(
                Span(child[0], float(child[1]), float(child[2]))
            )
        self._attach(sp)
        return sp

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def find(self, name: str) -> List[Span]:
        """All spans with ``name``, depth-first (test/bench readback)."""
        out: List[Span] = []
        stack = list(self.spans)
        while stack:
            sp = stack.pop()
            if sp.name == name:
                out.append(sp)
            stack.extend(sp.children)
        return out

    def to_dict(self) -> dict:
        out = {
            "trace_id": self.trace_id,
            "wall": round(self.wall, 3),
            "spans": [s.to_dict() for s in self.spans],
        }
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.dropped:
            out["dropped_spans"] = self.dropped
        return out


def new_trace_id() -> str:
    """16 hex chars of OS entropy — unique across agents of a fleet
    (the service keys server-side spans by it)."""
    return os.urandom(8).hex()


# ---------------------------------------------------------------------------
# ambient (current-tick) trace

_ACTIVE = threading.local()


def start_trace(trace: Optional[Trace] = None) -> Trace:
    """Install ``trace`` (or a fresh one) as this thread's current
    trace; spans emitted via ``span(...)``/``phase(...)`` nest into it."""
    t = trace or Trace()
    _ACTIVE.trace = t
    return t


def end_trace(trace: Trace) -> None:
    if getattr(_ACTIVE, "trace", None) is trace:
        _ACTIVE.trace = None


def current_trace() -> Optional[Trace]:
    return getattr(_ACTIVE, "trace", None)


def current_trace_id() -> str:
    t = current_trace()
    return t.trace_id if t is not None else ""


@contextlib.contextmanager
def tick_trace(enabled: bool = True):
    """Scope one tick (or one standalone plan) under a fresh ambient
    trace; yields it (None when disabled)."""
    if not enabled:
        yield None
        return
    t = start_trace()
    try:
        yield t
    finally:
        end_trace(t)


@contextlib.contextmanager
def span(name: str, **attrs):
    """A span on the ambient trace — free (yields None) when no trace
    is active, so instrumented call sites cost one thread-local read
    on the untraced path."""
    t = current_trace()
    if t is None:
        yield None
        return
    with t.span(name, **attrs) as sp:
        yield sp


def make_span(name: str, t0_ms: float, dur_ms: float) -> Tuple[str, float, float]:
    """An already-measured ``(name, t0_ms, dur_ms)`` tuple — the compact
    form spans travel in over the service wire and graft back from."""
    return (name, float(t0_ms), float(dur_ms))


# ---------------------------------------------------------------------------
# phase timers + optional jax.profiler annotation

_trace_dir: Optional[str] = None


def enable_profiler(trace_dir: str) -> None:
    """Route subsequent ``phase(...)`` blocks through jax.profiler traces
    written to ``trace_dir``."""
    global _trace_dir
    _trace_dir = trace_dir


def disable_profiler() -> None:
    global _trace_dir
    _trace_dir = None


@contextlib.contextmanager
def phase(name: str):
    """Time one tick phase into metrics (+ a span on the ambient trace,
    + profiler annotation if on). The duration is recorded even when
    the body raises — the span then carries ``error: true`` — so an
    error-skipped tick still explains where its time went."""
    start = time.perf_counter()
    ctx = contextlib.nullcontext()
    if _trace_dir is not None:
        try:
            import jax.profiler

            ctx = jax.profiler.TraceAnnotation(name)
        except Exception as err:  # noqa: BLE001 — profiling is best-effort
            log.vlog(2, "profiler unavailable: %s", err)
    t = current_trace()
    sctx = t.span(name) if t is not None else contextlib.nullcontext()
    try:
        with ctx, sctx:
            yield
    finally:
        metrics.observe_tick_phase(name, time.perf_counter() - start)


@contextlib.contextmanager
def device_trace():
    """Wrap a region in a jax.profiler trace dump (one file per call)."""
    if _trace_dir is None:
        yield
        return
    try:
        import jax.profiler

        with jax.profiler.trace(_trace_dir):
            yield
    except Exception as err:  # noqa: BLE001
        log.vlog(2, "device trace failed: %s", err)
        yield
