"""Tracing/profiling hooks.

The reference's only observability into its hot path is glog verbosity
(SURVEY.md §5.1); here each tick phase is timed into a Prometheus
histogram (metrics/registry.py ``tick_phase_duration``) and, when a trace
directory is configured, device work runs under ``jax.profiler`` so the
solver's XLA/Pallas execution shows up in TensorBoard/Perfetto.

Phases of the pipelined tick (loop/controller.py): ``observe`` (cluster
state + PDBs), ``plan-dispatch`` (host pack + delta-upload + async solve
dispatch), ``observe-metrics`` (per-node metrics — host work that runs
WHILE the device solve is in flight), ``plan-fetch`` (the blocking
selection fetch + report build), ``actuate``. The aggregate ``plan``
series (dispatch + fetch, excluding the overlapped window) is kept for
dashboard continuity; ``plan-fetch`` minus the true device time is the
residual the overlap did not hide.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.utils import logging as log

_trace_dir: Optional[str] = None


def enable_profiler(trace_dir: str) -> None:
    """Route subsequent ``phase(...)`` blocks through jax.profiler traces
    written to ``trace_dir``."""
    global _trace_dir
    _trace_dir = trace_dir


@contextlib.contextmanager
def phase(name: str):
    """Time one tick phase into metrics (+ profiler annotation if on)."""
    start = time.perf_counter()
    ctx = contextlib.nullcontext()
    if _trace_dir is not None:
        try:
            import jax.profiler

            ctx = jax.profiler.TraceAnnotation(name)
        except Exception as err:  # noqa: BLE001 — profiling is best-effort
            log.vlog(2, "profiler unavailable: %s", err)
    with ctx:
        yield
    metrics.observe_tick_phase(name, time.perf_counter() - start)


@contextlib.contextmanager
def device_trace():
    """Wrap a region in a jax.profiler trace dump (one file per call)."""
    if _trace_dir is None:
        yield
        return
    try:
        import jax.profiler

        with jax.profiler.trace(_trace_dir):
            yield
    except Exception as err:  # noqa: BLE001
        log.vlog(2, "device trace failed: %s", err)
        yield
