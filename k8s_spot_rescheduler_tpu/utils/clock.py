"""Injectable time source.

The reference calls ``time.Now()``/``time.After``/``time.Sleep`` directly
(rescheduler.go:159-167, scaler/scaler.go:47-62, 119-144), which is why its
control loop and actuator are untested (SURVEY.md §4). The framework routes
all time through a ``Clock`` so the loop/actuator state machines are unit
testable with a virtual clock.
"""

from __future__ import annotations

import heapq
import threading
import time as _time
from typing import Protocol


class Clock(Protocol):
    def now(self) -> float: ...
    def sleep(self, seconds: float) -> None: ...
    # wall-clock epoch seconds: unlike ``now`` (monotonic — resets with
    # the process), comparable across restarts and replicas; used for
    # durable timestamps written into the cluster (taint ownership)
    def wall(self) -> float: ...


class RealClock:
    def now(self) -> float:
        return _time.monotonic()

    def wall(self) -> float:
        return _time.time()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            _time.sleep(seconds)


class FakeClock:
    """Deterministic virtual clock. ``sleep`` advances time instantly and
    fires any timers scheduled via ``call_at`` (used by the fake cluster to
    model pod-termination latency)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._timers: list = []  # heap of (when, seq, fn)
        self._seq = 0
        # the actuator's eviction fan-out schedules termination timers
        # from worker threads (actuator/drain.py)
        self._lock = threading.Lock()

    def now(self) -> float:
        return self._now

    def wall(self) -> float:
        # the virtual timeline IS the wall clock in tests
        return self._now

    def call_at(self, when: float, fn) -> None:
        with self._lock:
            heapq.heappush(self._timers, (float(when), self._seq, fn))
            self._seq += 1

    def sleep(self, seconds: float) -> None:
        self.advance(max(0.0, seconds))

    def advance(self, seconds: float) -> None:
        deadline = self._now + float(seconds)
        while True:
            with self._lock:
                if not self._timers or self._timers[0][0] > deadline:
                    break
                when, _, fn = heapq.heappop(self._timers)
                self._now = max(self._now, when)
            fn()  # outside the lock: fn may schedule follow-up timers
        with self._lock:  # call_at readers see a coherent (_now, heap)
            self._now = deadline
