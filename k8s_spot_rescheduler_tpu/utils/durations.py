"""Go-style duration parsing ("10s", "10m", "2h30m") for CLI parity with
the reference's ``flags.Duration`` flags (reference rescheduler.go:63-75)."""

from __future__ import annotations

import re

_UNIT = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}
_TOKEN = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")


def parse_duration(s: str | float | int) -> float:
    """Duration string → seconds. Bare numbers are taken as seconds."""
    if isinstance(s, (int, float)):
        return float(s)
    s = s.strip()
    try:
        return float(s)
    except ValueError:
        pass
    pos = 0
    total = 0.0
    for m in _TOKEN.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {s!r}")
        total += float(m.group(1)) * _UNIT[m.group(2)]
        pos = m.end()
    if pos != len(s) or pos == 0:
        raise ValueError(f"invalid duration {s!r}")
    return total
