"""Node-class label matching.

The reference supports two label schemas — a bare ``key`` ("old schema")
present-check and a ``key=value`` ("new schema") equality check — for both
the spot and on-demand node classes (reference nodes/nodes.go:167-209
``isSpotNode``/``isOnDemandNode``), and validates at startup that a label
has at most one ``=`` (reference rescheduler.go:407-417 ``validateArgs``).
"""

from __future__ import annotations

from typing import Mapping


class LabelFormatError(ValueError):
    """Raised for a label with more than one '='."""


def validate_label(label: str, what: str = "node label") -> None:
    """Reject labels that are not ``key`` or ``key=value``.

    Mirrors reference rescheduler.go:407-417: splitting on "=" must yield
    at most two parts.
    """
    if len(label.split("=")) > 2:
        raise LabelFormatError(
            f"the {what} is not correctly formatted: expected '<label_name>' "
            f"or '<label_name>=<label_value>', but got {label}"
        )


def matches_label(node_labels: Mapping[str, str], selector: str) -> bool:
    """True if ``node_labels`` satisfies ``selector``.

    ``selector`` is either a bare key (matches if the key is present with
    any value, reference nodes/nodes.go:173-176) or ``key=value`` (matches
    on exact value, nodes/nodes.go:177-184). SplitN(=, 2) semantics: only
    the first '=' separates key from value.
    """
    key, sep, value = selector.partition("=")
    if not sep:
        return key in node_labels
    return node_labels.get(key) == value
