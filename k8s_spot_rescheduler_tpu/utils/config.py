"""Framework configuration.

Replaces the reference's flag surface (reference rescheduler.go:48-108) and
the cross-package mutable globals it writes into (reference
nodes/nodes.go:31-42: ``OnDemandNodeLabel``/``SpotNodeLabel``/
``PriorityThreshold``) with one explicit, immutable dataclass that is passed
down the stack.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class ReschedulerConfig:
    """All knobs of the rescheduler, with the reference's defaults.

    Field-by-field parity with the reference flags (citations are into
    /root/reference):

    - ``running_in_cluster``      — rescheduler.go:53-55
    - ``namespace``               — rescheduler.go:57-58
    - ``housekeeping_interval``   — rescheduler.go:63-64 (10 s)

    Deliberately absent: the reference's ``--kube-api-content-type``
    (rescheduler.go:60-61). This client is JSON-only; the decode-cost
    problem protobuf solves is answered here by the native columnar
    ingest engine (native/ingest.cc). Carrying a flag the client ignores
    would mislead operators.
    - ``node_drain_delay``        — rescheduler.go:66-67 (10 min)
    - ``pod_eviction_timeout``    — rescheduler.go:69-71 (2 min)
    - ``max_graceful_termination``— rescheduler.go:73-75 (2 min)
    - ``listen_address``          — rescheduler.go:77-78
    - ``kubeconfig``              — rescheduler.go:82
    - ``delete_non_replicated_pods`` — rescheduler.go:84
    - ``on_demand_node_label``    — rescheduler.go:98-101
    - ``spot_node_label``         — rescheduler.go:102-105
    - ``priority_threshold``      — rescheduler.go:107-108
    - ``eviction_retry_time``     — scaler/scaler.go:37-38 (10 s; a const
      in the reference, a knob here)

    TPU-native additions (no reference equivalent):

    - ``resources``     — which resource dimensions the solver packs into the
      request/allocatable tensors. The reference plans on CPU millicores only
      (nodes/nodes.go:149-165); the full predicate checker it delegates to
      checks cpu/mem/pods (README.md:103-114).
    - ``max_pods_per_node_hint`` — static padding bound for the solver's pod
      axis; the packer grows it if a node exceeds the hint.
    - ``solver``        — which solver backend plans the drain
      ("jax", "numpy", "pallas", "sharded").
    - ``mesh_shape``    — (candidate-axis, spot-axis) device mesh for the
      sharded solver.
    - ``max_drains_per_tick`` — the reference hard-codes one drain per tick
      (rescheduler.go:286 ``break``); keep 1 for faithful behavior.
    - ``fallback_best_fit`` — candidates unprovable under the reference's
      first-fit probe get a second feasibility pass under best-fit-
      decreasing packing. Placements remain predicate-valid, so this can
      only *add* drainable nodes (quality ≥ reference); disable for
      bit-faithful drain selection.
    - ``repair_rounds`` — bounded eject-and-reinsert local-search rounds
      (solver/repair.py) for lanes both greedy passes fail; repaired
      placements are re-proven from scratch before use. 0 disables.
    - ``auto_shard`` — when the packed problem's estimated footprint
      exceeds one chip's HBM (solver/memory.py) and more than one device
      is visible, the planner automatically reroutes the solve to the
      mesh-sharded backends, three rungs deep: cand-only sharding with
      the full union program per lane block (repair intact); the same
      tier with the repair rounds spot-CHUNKED (elect-then-commit,
      solver/repair.plan_repair_chunked — bit-identical results) once a
      block's unchunked repair state exceeds a device; and only past
      even the fully-chunked estimate the 2-D cand×spot layout
      (first-fit ∪ best-fit; repair genuinely unavailable — a
      conservative tradeoff: fewer proven drains, never an invalid
      one, alarmed by ``repair_unavailable``). Off → the configured
      solver runs unconditionally and a past-HBM problem fails with
      the backend's own OOM.
    - ``solver_hbm_budget`` — per-device byte budget for that decision;
      0 = auto-detect from the backend (v5e default 16 GB x 0.85).
    """

    running_in_cluster: bool = True
    namespace: str = "kube-system"
    housekeeping_interval: float = 10.0
    node_drain_delay: float = 600.0
    pod_eviction_timeout: float = 120.0
    max_graceful_termination: float = 120.0
    listen_address: str = "localhost:9235"
    kubeconfig: str = ""
    delete_non_replicated_pods: bool = False
    on_demand_node_label: str = "kubernetes.io/role=worker"
    spot_node_label: str = "kubernetes.io/role=spot-worker"
    priority_threshold: int = 0
    eviction_retry_time: float = 10.0

    # TPU-native knobs
    resources: Sequence[str] = ("cpu", "memory")
    max_pods_per_node_hint: int = 64
    solver: str = "jax"
    mesh_shape: tuple = (1, 1)
    max_drains_per_tick: int = 1
    fallback_best_fit: bool = True
    repair_rounds: int = 8
    auto_shard: bool = True
    solver_hbm_budget: int = 0
    # Carry-streamed tier chunk count (solver/fallback.
    # with_repair_streamed): how many ordered spot chunks the narrow
    # delta-carry union streams through when the auto-shard ladder
    # reaches the carry tier (past even the spot-chunked wide repair
    # ceiling — repair stays live, results bit-identical). 0 = auto via
    # solver/memory.pick_carry_chunks (sized to the device budget).
    carry_chunks: int = 0
    # Observe via the incrementally-maintained columnar mirror
    # (models/columnar.py) when the cluster client provides one — the
    # vectorized replacement for the per-tick object-model rebuild. Off →
    # always the reference-faithful object path.
    use_columnar: bool = True
    # Incremental device-resident tick pipeline (single-chip jax/pallas
    # paths; the mesh reroutes manage their own placement):
    # - ``incremental_device_cache`` keeps the previous tick's packed
    #   problem resident in device memory and ships only the churn delta
    #   (models/columnar.emit_packed_delta) each tick, applied in place
    #   via a donated-buffer scatter. Off → full upload every tick.
    # - ``staged_chunk_lanes`` solves candidate lanes in selection-order
    #   chunks of this size, skipping chunks the device prefilter
    #   (solver/prefilter.py) proves infeasible; 0 → unstaged full solve.
    # - ``staged_early_exit`` stops at the first chunk containing a
    #   feasible lane (the loop drains only the first feasible candidate,
    #   so the selection is identical); the reported feasible COUNT then
    #   covers the solved prefix only on ticks that found a drain.
    incremental_device_cache: bool = True
    staged_chunk_lanes: int = 256
    staged_early_exit: bool = True
    # Device-resident drain-to-exhaustion schedules (solver/schedule.py,
    # planner/schedule.py): one device fetch returns a whole drain
    # SCHEDULE (up to ``schedule_horizon`` steps) that the controller
    # executes across ticks, each step re-packed, precondition-checked,
    # and re-proven from scratch against the live mirror before any
    # eviction — churn invalidates the schedule tail (counted +
    # flight-evented) and forces a re-plan, never a wrong eviction.
    # Planner fetches for a consolidation sweep drop from O(drains) to
    # O(drains / horizon). ON by default since the PR-11 follow-up: the
    # quality-scale bench asserts the fetch bound with schedules live
    # and every step is still re-proven from scratch before any
    # eviction; ``--schedule-horizon 0`` is the documented opt-out
    # (per-tick single plans, the pre-schedule behavior).
    plan_schedule_enabled: bool = True
    # Max drain steps per cut schedule (the device while-loop bound and
    # the jit compile key; one compile per configured value). 0 turns
    # schedules OFF (the documented opt-out) even with
    # plan_schedule_enabled.
    schedule_horizon: int = 32
    # Persistent XLA compilation cache directory (``--jax-cache-dir``):
    # the solver programs cost seconds of cold compile per process
    # (~3.7 s at config-3 shapes, BENCH_r05); pointing this at a
    # volume-backed path pays that once per image, not per restart —
    # jax.config "jax_compilation_cache_dir", wired by SolverPlanner
    # before any program is built. Empty = no persistent cache.
    jax_cache_dir: str = ""
    # --- chaos hardening (docs/ROBUSTNESS.md) ---
    # Transient-failure retry policy for kube API READS (io/kube.py):
    # up to kube_retry_max additional attempts with jittered exponential
    # backoff from kube_retry_base seconds (Retry-After honored). Writes
    # stay single-attempt — the actuator owns eviction/taint cadence.
    kube_retry_max: int = 4
    kube_retry_base: float = 0.25
    # Observe-error circuit breaker (loop/controller.py): after this many
    # consecutive error-skipped ticks the effective housekeeping interval
    # doubles per further failure, capped at breaker_max_interval;
    # 0 disables the breaker.
    breaker_threshold: int = 3
    breaker_max_interval: float = 300.0
    # Crash-safe drain recovery: on startup and once per tick, remove
    # ToBeDeleted taints no active drain owns (an interrupted drain's
    # residue would otherwise permanently unschedule an on-demand node).
    reconcile_orphaned_taints: bool = True
    # Fault injection (io/chaos.py): wrap the cluster client in the
    # seeded chaos layer. Empty profile = off (production default).
    chaos_profile: str = ""
    chaos_seed: int = 0
    # Per-stream-open probability that an injected chaos watch stream is
    # open but SILENT until the client's read timeout (the wedged-stream
    # failure mode the progress deadline exists to catch). Mixed into
    # whatever --chaos-profile selects; 0 with chaos off is inert.
    chaos_watch_stall_rate: float = 0.0
    # --- freshness-gated observe path (docs/ROBUSTNESS.md) ---
    # Client-side watch progress deadline (io/watch.py): a stream that
    # delivers no event, bookmark, or clean server close for this long
    # is killed and reconnected from its last resourceVersion
    # (client-go's WatchProgressRequester/UnwedgeTimeout analog — the
    # server-side timeoutSeconds alone cannot catch a wedged transport).
    # 0 disables (server timeouts only).
    watch_progress_deadline: float = 120.0
    # Freshness gate (loop/controller.py): a tick whose watch mirror is
    # older than this budget refuses to plan from it — it degrades to a
    # direct apiserver LIST, or skips the tick (feeding the circuit
    # breaker) when no direct path exists. 0 disables the gate.
    mirror_staleness_budget: float = 60.0
    # --- multi-tenant planner service (service/, docs/DESIGN.md §11) ---
    # Agent mode: plan through a remote planner service instead of the
    # in-process solver. The per-cluster agent keeps observe/pack/
    # actuate local (chaos-hardened, PR 4) and ships only packed
    # tensors over the binary wire protocol (service/wire.py); on
    # service failure it degrades through the numpy-oracle fallback +
    # circuit breaker (remote_planner_fallback_total). Empty = plan
    # in-process (the reference topology).
    planner_url: str = ""
    # Fleet failover (docs/ROBUSTNESS.md "Fleet failure domains"): an
    # ORDERED comma-separated list of planner-service endpoints. Each
    # endpoint carries its own consecutive-failure breaker; a tick walks
    # the list in order and fails over past dead/overloaded/breaker-open
    # replicas, falling back to the in-process numpy oracle only when
    # every endpoint is unusable. Takes precedence over ``planner_url``
    # (which itself also accepts a comma list, kept as the
    # single-endpoint spelling).
    planner_urls: str = ""
    # Per-plan HTTP deadline of the agent's service call; past it the
    # tick falls back locally rather than stall the control loop.
    planner_timeout: float = 10.0
    # Delta wire (docs/ROBUSTNESS.md "Wire anti-entropy", wire v4): a
    # RemotePlanner agent ships each tick's churn-proportional
    # PackedDelta instead of the full pack whenever the endpoint it is
    # about to try acknowledged the exact previous pack (fingerprint-
    # tracked per endpoint — failover forces a full pack by itself).
    # The service applies deltas to its fingerprinted per-tenant
    # device-resident cache; ANY disagreement — restart, eviction,
    # mismatch, corruption — is answered with a typed resync demand
    # and costs one full pack, never a wrong plan. Off = every tick
    # ships the full pack (the pre-v4 behavior).
    delta_wire_enabled: bool = True
    # Device-health watchdog (service/devhealth.py): consecutive
    # slower-than-baseline batched solves before the planner service
    # declares its accelerator sick and flips to the numpy-oracle host
    # path (``/healthz`` device:"sick", ``service_device_sick`` gauge,
    # flight ``device-sick`` event; hysteresis-gated recovery probes).
    # 0 disables the watchdog.
    device_sick_threshold: int = 3
    # Graceful drain (SIGTERM): seconds the service lets already-queued
    # batches finish before evicting the rest with 503; new arrivals are
    # refused immediately with Retry-After = this grace.
    service_drain_grace: float = 5.0
    # Warm restart: directory the service persists per-tenant last-pack
    # fingerprints and the recently-used bucket list into, and pre-warms
    # those bucket compiles from on boot (a restarted replica must not
    # eat a compile storm from N reconnecting agents). Empty = cold
    # restarts.
    service_state_dir: str = ""
    # Service-path fault injection (service/chaos.py): seeded wire/HTTP/
    # solve faults on the agent transport and the service solve hook.
    # Empty profile = off (production default) — testing/demo only.
    service_chaos_profile: str = ""
    service_chaos_seed: int = 0
    # Service batching window: how long the scheduler waits after work
    # arrives to coalesce concurrent tenants into one batched solve.
    # 0 = dispatch immediately (every request solves alone).
    service_batch_window: float = 0.02
    # Bounded queue wait: a plan request still unbatched past this is
    # evicted with 503 + Retry-After derived from the measured batch
    # cadence (service_tenant_evictions_total, per tenant).
    service_queue_timeout: float = 30.0
    # Resync-storm admission class (docs/ROBUSTNESS.md "Resync
    # storms"): max full-pack resync ingests (fingerprinted full pack
    # for a tenant with no cached state — first contact or post-restart
    # re-seed) allowed in flight at once. A replica restart under a
    # large fleet stales every tenant's fingerprint simultaneously;
    # this token bucket keeps the correlated full-pack herd from
    # starving delta traffic — excess ingests are refused with a typed
    # 503 + load-derived Retry-After (shed reason resync-storm) instead
    # of collapsing the queue.
    service_resync_ingest_cap: int = 4
    # Byte budget for the resync-ingest ledger: in-flight resync
    # ingests charge their per-tenant HBM footprint (the same
    # estimate_union_hbm_breakdown model that sizes the batch cap)
    # against this. 0 = derive from solver_hbm_budget / the device HBM
    # budget. One over-budget ingest is still admitted when the class
    # is idle (mirrors the batch cap's never-zero floor).
    service_resync_ingest_budget: int = 0
    # Anti-entropy resync audit (io/watch.py): every interval, one
    # LIST per watched resource is diffed field-by-field against the
    # incremental mirror; drift forces a store replace + full repack
    # and is counted, evented, and never silent (client-go informers'
    # resyncPeriod analog, upgraded from blind replay to a verified
    # diff). Runs inline on the tick thread — one tick per interval
    # pays the LIST cost; background in cadence, not threading.
    # 0 disables.
    resync_interval: float = 300.0
    # --- tick tracing + flight recorder (docs/OBSERVABILITY.md) ---
    # Per-tick span-tree tracing (utils/tracing.py): a tick-scoped
    # trace ID threaded through observe/plan/actuate, the kube read
    # path, and — in agent mode — across the planner-service wire
    # (X-Trace-Id + wire v2 trace frame; server spans graft back into
    # the tick tree). Always-on-cheap (O(spans) host work, no device
    # syncs); off = the phase histograms alone, as before.
    trace_enabled: bool = True
    # Flight recorder (loop/flight.py): how many completed tick traces
    # the in-memory postmortem ring retains.
    flight_ring_size: int = 64
    # Directory the flight recorder auto-dumps a redacted JSON
    # postmortem into whenever a degradation edge fires (planner
    # fallback, breaker engage, freshness bypass, watch stall, service
    # shed); empty = never write to disk (ring + /debug only).
    flight_dump_dir: str = ""
    # Serve GET /debug/trace (last tick tree) and /debug/flight (ring
    # summary + dump trigger) on the sidecar/service HTTP servers.
    # Off by default: debug surfaces are opt-in, never ambient.
    debug_endpoints: bool = False

    def __post_init__(self):
        from k8s_spot_rescheduler_tpu.utils.labels import validate_label

        validate_label(self.on_demand_node_label, "on demand node label")
        validate_label(self.spot_node_label, "spot node label")
        if self.max_drains_per_tick < 1:
            raise ValueError("max_drains_per_tick must be >= 1")
        if self.staged_chunk_lanes < 0:
            raise ValueError("staged_chunk_lanes must be >= 0 (0 = unstaged)")
        if self.carry_chunks < 0:
            raise ValueError("carry_chunks must be >= 0 (0 = auto)")
        if self.schedule_horizon < 0:
            raise ValueError(
                "schedule_horizon must be >= 0 (0 = schedules off)"
            )
        if not self.resources:
            raise ValueError("resources must be non-empty")
        if self.kube_retry_max < 0:
            raise ValueError("kube_retry_max must be >= 0 (0 = no retries)")
        if self.kube_retry_base <= 0:
            raise ValueError("kube_retry_base must be > 0")
        if self.breaker_threshold < 0:
            raise ValueError("breaker_threshold must be >= 0 (0 = off)")
        if self.watch_progress_deadline < 0:
            raise ValueError(
                "watch_progress_deadline must be >= 0 (0 = off)"
            )
        if self.mirror_staleness_budget < 0:
            raise ValueError(
                "mirror_staleness_budget must be >= 0 (0 = off)"
            )
        if self.resync_interval < 0:
            raise ValueError("resync_interval must be >= 0 (0 = off)")
        if self.planner_timeout <= 0:
            raise ValueError("planner_timeout must be > 0")
        if self.service_batch_window < 0:
            raise ValueError(
                "service_batch_window must be >= 0 (0 = no coalescing)"
            )
        if self.service_queue_timeout <= 0:
            raise ValueError("service_queue_timeout must be > 0")
        if self.service_resync_ingest_cap < 1:
            raise ValueError(
                "service_resync_ingest_cap must be >= 1 (the class "
                "must admit at least one ingest or no tenant can ever "
                "seed its cache)"
            )
        if self.service_resync_ingest_budget < 0:
            raise ValueError(
                "service_resync_ingest_budget must be >= 0 (0 = derive "
                "from the HBM budget)"
            )
        if self.device_sick_threshold < 0:
            raise ValueError(
                "device_sick_threshold must be >= 0 (0 = watchdog off)"
            )
        if self.service_drain_grace < 0:
            raise ValueError(
                "service_drain_grace must be >= 0 (0 = evict queued "
                "work immediately on drain)"
            )
        from k8s_spot_rescheduler_tpu.service.chaos import ServiceFaultPlan

        if self.service_chaos_profile not in ServiceFaultPlan.PROFILES:
            raise ValueError(
                f"unknown service_chaos_profile "
                f"{self.service_chaos_profile!r} "
                f"(known: {', '.join(p for p in ServiceFaultPlan.PROFILES if p)})"
            )
        if not 0.0 <= self.chaos_watch_stall_rate <= 1.0:
            raise ValueError(
                "chaos_watch_stall_rate must be a probability in [0, 1]"
            )
        if self.flight_ring_size < 1:
            raise ValueError("flight_ring_size must be >= 1")
