"""Process entry point.

Flag-for-flag parity with the reference's CLI (reference
rescheduler.go:48-142: 13 pflag flags + glog's -v + --version), plus the
TPU-native knobs (solver backend, resources, mesh) and a cluster source
selector: the reference always talks to a live apiserver; this framework
additionally runs against synthetic clusters (demo/benchmark mode) behind
the same ClusterClient interface.

Run e.g.::

    python -m k8s_spot_rescheduler_tpu --cluster synthetic:1 --ticks 3 -v 2
"""

from __future__ import annotations

import argparse
import sys

from k8s_spot_rescheduler_tpu import VERSION
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils.durations import parse_duration
from k8s_spot_rescheduler_tpu.utils.labels import LabelFormatError
from k8s_spot_rescheduler_tpu.utils import logging as log


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="k8s-spot-rescheduler-tpu",
        description="TPU-native spot rescheduler",
    )
    d = ReschedulerConfig()
    # --- reference flag surface (rescheduler.go:48-108) ---
    p.add_argument("--running-in-cluster", type=_bool, default=d.running_in_cluster,
                   help="use in-cluster credentials (reference rescheduler.go:53)")
    p.add_argument("--namespace", default=d.namespace)
    # NOTE: the reference's --kube-api-content-type (rescheduler.go:60-61,
    # protobuf wire format) is deliberately NOT reproduced: this client
    # speaks JSON only, and its answer to protobuf's decode-cost
    # motivation is the native columnar ingest engine (native/ingest.cc),
    # which decodes a 50k-pod JSON LIST faster than the Python protobuf
    # path could. A flag that silently did nothing would be worse than
    # no flag.
    p.add_argument("--housekeeping-interval", default="10s",
                   help="how often rescheduler takes actions (Go duration)")
    p.add_argument("--node-drain-delay", default="10m",
                   help="wait between draining nodes")
    p.add_argument("--pod-eviction-timeout", default="2m")
    p.add_argument("--max-graceful-termination", default="2m")
    p.add_argument("--listen-address", default=d.listen_address,
                   help="prometheus metrics address")
    p.add_argument("--kubeconfig", default=d.kubeconfig)
    p.add_argument("--delete-non-replicated-pods", type=_bool,
                   default=d.delete_non_replicated_pods)
    p.add_argument("--on-demand-node-label", default=d.on_demand_node_label)
    p.add_argument("--spot-node-label", default=d.spot_node_label)
    p.add_argument("--priority-threshold", type=int, default=d.priority_threshold)
    p.add_argument("--eviction-retry-time", default=f"{d.eviction_retry_time:g}s",
                   help="pause between eviction retry rounds while a "
                        "drain waits pods out (a const in the reference, "
                        "scaler/scaler.go:37-38; Go duration)")
    p.add_argument("--version", action="store_true", help="show version and exit")
    p.add_argument("-v", "--verbosity", type=int, default=0, help="glog-style -v")
    # --- TPU-native knobs ---
    p.add_argument("--solver", default=d.solver,
                   choices=["jax", "numpy", "pallas", "sharded"])
    p.add_argument("--mesh-shape", default="",
                   help="cand x spot device mesh for --solver sharded, "
                        "e.g. 4x2 (default: infer from visible devices)")
    p.add_argument("--resources", default=",".join(d.resources),
                   help="comma-separated resource axes to pack")
    p.add_argument("--repair-rounds", type=int, default=d.repair_rounds,
                   help="eject-and-reinsert local-search rounds for "
                        "candidates greedy packing can't prove (0=off)")
    p.add_argument("--fallback-best-fit", type=_bool,
                   default=d.fallback_best_fit,
                   help="second feasibility pass under best-fit-"
                        "decreasing packing for candidates first-fit "
                        "can't prove (only ever adds drainable nodes; "
                        "false = bit-faithful reference selection)")
    p.add_argument("--max-drains-per-tick", type=int,
                   default=d.max_drains_per_tick,
                   help="drains per housekeeping tick (the reference "
                        "hard-codes 1, rescheduler.go:286; >1 re-plans "
                        "between drains)")
    p.add_argument("--max-pods-per-node-hint", type=int,
                   default=d.max_pods_per_node_hint,
                   help="static padding bound for the solver's pod-slot "
                        "axis (grown automatically when a node exceeds "
                        "it, at the cost of a recompile)")
    p.add_argument("--use-columnar", type=_bool, default=d.use_columnar,
                   help="observe via the incrementally-maintained "
                        "columnar mirror when the cluster source "
                        "provides one; false = the reference-faithful "
                        "per-tick object rebuild")
    p.add_argument("--auto-shard", type=_bool, default=d.auto_shard,
                   help="reroute the solve to the mesh-sharded backend "
                        "automatically when the problem exceeds one "
                        "chip's HBM and >1 device is visible")
    p.add_argument("--solver-hbm-budget", type=int,
                   default=d.solver_hbm_budget,
                   help="per-device byte budget for the auto-shard "
                        "decision (0 = auto-detect from the backend)")
    p.add_argument("--carry-chunks", type=int, default=d.carry_chunks,
                   help="spot-chunk count of the carry-streamed narrow "
                        "union tier (the auto-shard rung past the wide "
                        "chunked-repair ceiling; repair stays live, "
                        "results bit-identical); 0 = auto via "
                        "solver/memory.pick_carry_chunks")
    p.add_argument("--incremental-device-cache", type=_bool,
                   default=d.incremental_device_cache,
                   help="keep the packed problem resident on device and "
                        "ship only the per-tick churn delta (donated "
                        "scatter update); off = full upload every tick")
    p.add_argument("--staged-chunk-lanes", type=int,
                   default=d.staged_chunk_lanes,
                   help="solve candidate lanes in selection-order chunks "
                        "of this size, skipping prefilter-eliminated "
                        "chunks (0 = unstaged full solve)")
    p.add_argument("--staged-early-exit", type=_bool,
                   default=d.staged_early_exit,
                   help="stop solving at the first chunk containing a "
                        "feasible lane (selection is identical; the "
                        "feasible count then covers the solved prefix)")
    p.add_argument("--plan-schedule-enabled", type=_bool,
                   default=d.plan_schedule_enabled,
                   help="cut whole drain-to-exhaustion SCHEDULES on "
                        "device (one planner fetch per schedule-horizon "
                        "drains) and execute them across ticks, each "
                        "step re-packed and re-proven from scratch "
                        "against the live mirror before any eviction; "
                        "churn invalidates the schedule tail and "
                        "re-plans; ON by default (false, or "
                        "--schedule-horizon 0, = per-tick single plans)")
    p.add_argument("--schedule-horizon", type=int,
                   default=d.schedule_horizon,
                   help="max drain steps per cut schedule (the device "
                        "while-loop bound and its jit compile key); "
                        "0 = schedules off (the documented opt-out)")
    p.add_argument("--kube-retry-max", type=int, default=d.kube_retry_max,
                   help="max transient-retry attempts per kube API read "
                        "(429/5xx/connection errors, jittered exponential "
                        "backoff honoring Retry-After; writes are "
                        "single-attempt — the actuator owns their cadence)")
    p.add_argument("--kube-retry-base", type=float, default=d.kube_retry_base,
                   help="base seconds of the kube read retry backoff")
    p.add_argument("--breaker-threshold", type=int, default=d.breaker_threshold,
                   help="consecutive error-skipped ticks before the "
                        "circuit breaker widens the housekeeping interval "
                        "(0 = off)")
    p.add_argument("--breaker-max-interval",
                   default=f"{d.breaker_max_interval:g}s",
                   help="cap of the breaker-widened interval (Go duration)")
    p.add_argument("--reconcile-orphaned-taints", type=_bool,
                   default=d.reconcile_orphaned_taints,
                   help="on startup and each tick, remove ToBeDeleted "
                        "taints no active drain owns (crash-safe drain "
                        "recovery; the reference leaves them for CA)")
    from k8s_spot_rescheduler_tpu.io.chaos import FaultPlan as _FaultPlan

    p.add_argument("--chaos-profile", default=d.chaos_profile,
                   choices=list(_FaultPlan.PROFILES),
                   help="wrap the cluster client in the seeded "
                        "fault-injection layer (io/chaos.py) — "
                        "testing/demo only, never production")
    p.add_argument("--chaos-seed", type=int, default=d.chaos_seed,
                   help="seed of the chaos fault stream (deterministic)")
    p.add_argument("--chaos-watch-stall-rate", type=float,
                   default=d.chaos_watch_stall_rate,
                   help="per-stream-open probability an injected chaos "
                        "watch stream is open but silent until the read "
                        "timeout (the wedged-stream failure mode the "
                        "progress deadline catches); mixed into the "
                        "selected --chaos-profile")
    p.add_argument("--watch-progress-deadline", default="2m",
                   help="kill and reconnect a watch stream that delivers "
                        "no event, bookmark, or clean close for this "
                        "long — open-but-silent streams must not serve "
                        "the mirror forever (Go duration; 0 = server "
                        "timeouts only)")
    p.add_argument("--mirror-staleness-budget", default="1m",
                   help="refuse to plan a tick from a watch mirror older "
                        "than this: the tick degrades to a direct LIST, "
                        "or skips into the circuit breaker (Go duration; "
                        "0 disables the freshness gate)")
    p.add_argument("--resync-interval", default="5m",
                   help="anti-entropy audit period: a background LIST is "
                        "diffed field-by-field against the watch mirror; "
                        "drift is counted, evented, and healed by a "
                        "store replace (Go duration; 0 disables)")
    p.add_argument("--planner-url", default=d.planner_url,
                   help="plan through a remote multi-tenant planner "
                        "service at this base URL instead of the "
                        "in-process solver: observe/pack/actuate stay "
                        "local, packed tensors ship over the binary "
                        "wire protocol (service/wire.py); on failure "
                        "the tick falls back to the local numpy oracle "
                        "(empty = plan in-process)")
    p.add_argument("--planner-urls", default=d.planner_urls,
                   help="ORDERED comma-separated planner-service "
                        "endpoints: per-endpoint circuit breakers, "
                        "failover down the list on failure/breaker-open, "
                        "local numpy-oracle fallback only when every "
                        "endpoint is dead (takes precedence over "
                        "--planner-url)")
    p.add_argument("--planner-timeout", default=f"{d.planner_timeout:g}s",
                   help="per-plan HTTP deadline of the agent's planner-"
                        "service call; past it the tick plans locally "
                        "(Go duration)")
    p.add_argument("--delta-wire-enabled", type=_bool,
                   default=d.delta_wire_enabled,
                   help="ship each tick's churn-proportional delta to "
                        "the planner service instead of the full pack "
                        "(wire v4, fingerprinted per endpoint); the "
                        "service resyncs with one full pack on restart/"
                        "eviction/mismatch/corruption — resync-on-"
                        "anything, never a wrong plan (false = full "
                        "packs every tick)")
    p.add_argument("--device-sick-threshold", type=int,
                   default=d.device_sick_threshold,
                   help="--serve mode: consecutive slower-than-baseline "
                        "batched solves before the device-health "
                        "watchdog declares the accelerator sick and "
                        "flips the service to the numpy-oracle host "
                        "path (0 = watchdog off)")
    p.add_argument("--service-drain-grace",
                   default=f"{d.service_drain_grace:g}s",
                   help="--serve mode: seconds SIGTERM lets queued "
                        "batches finish before the rest are evicted "
                        "with 503; new arrivals get Retry-After = this "
                        "grace (Go duration)")
    p.add_argument("--service-state-dir", default=d.service_state_dir,
                   help="--serve mode: persist per-tenant pack "
                        "fingerprints + the bucket warmup list here and "
                        "pre-warm those compiles on boot (warm restart; "
                        "empty = cold restarts)")
    from k8s_spot_rescheduler_tpu.service.chaos import (
        ServiceFaultPlan as _ServiceFaultPlan,
    )

    p.add_argument("--service-chaos-profile",
                   default=d.service_chaos_profile,
                   choices=list(_ServiceFaultPlan.PROFILES),
                   help="seeded fault injection on the planner-service "
                        "path (service/chaos.py): wire faults on the "
                        "agent transport, solve/decode faults in the "
                        "service — testing/demo only, never production")
    p.add_argument("--service-chaos-seed", type=int,
                   default=d.service_chaos_seed,
                   help="seed of the service chaos fault stream "
                        "(deterministic)")
    p.add_argument("--service-batch-window",
                   default=f"{d.service_batch_window:g}s",
                   help="--serve mode: how long the batching scheduler "
                        "waits to coalesce concurrent tenants into one "
                        "batched solve (Go duration; 0 = dispatch "
                        "immediately)")
    p.add_argument("--service-queue-timeout",
                   default=f"{d.service_queue_timeout:g}s",
                   help="--serve mode: bounded queue wait — a plan "
                        "request unbatched past this is evicted with "
                        "503 + Retry-After from the measured batch "
                        "cadence (Go duration)")
    p.add_argument("--service-resync-ingest-cap", type=int,
                   default=d.service_resync_ingest_cap,
                   help="--serve mode: max concurrent full-pack resync "
                        "ingests (uncached tenants re-seeding after a "
                        "restart); excess refused with typed 503 + "
                        "load-derived Retry-After so a correlated "
                        "resync storm sheds instead of collapsing")
    p.add_argument("--service-resync-ingest-budget", type=int,
                   default=d.service_resync_ingest_budget,
                   help="--serve mode: byte budget for the resync "
                        "ingest ledger (in-flight resync ingests "
                        "charge their estimated per-tenant HBM "
                        "footprint); 0 = derive from the HBM budget")
    p.add_argument("--serve", default="",
                   help="run as the multi-tenant planner SERVICE on "
                        "this address (e.g. 0.0.0.0:8642) instead of a "
                        "control loop: /v2/plan (binary wire), /v1/plan "
                        "(JSON adapter), /healthz; one TPU plans for a "
                        "fleet of --planner-url agents")
    p.add_argument("--trace-enabled", type=_bool, default=d.trace_enabled,
                   help="per-tick span-tree tracing with wire-propagated "
                        "trace IDs (utils/tracing.py; always-on-cheap — "
                        "O(spans) host work, no device syncs); false = "
                        "phase histograms only")
    p.add_argument("--flight-ring-size", type=int,
                   default=d.flight_ring_size,
                   help="completed tick traces the flight recorder's "
                        "in-memory postmortem ring retains "
                        "(loop/flight.py)")
    p.add_argument("--flight-dump-dir", default=d.flight_dump_dir,
                   help="directory the flight recorder auto-dumps a "
                        "redacted JSON postmortem into when a "
                        "degradation edge fires (planner fallback, "
                        "breaker engage, freshness bypass, watch stall, "
                        "service shed); empty = in-memory ring only")
    p.add_argument("--debug-endpoints", type=_bool,
                   default=d.debug_endpoints,
                   help="serve GET /debug/trace and /debug/flight on "
                        "the sidecar/service HTTP servers (off by "
                        "default; debug surfaces are opt-in)")
    p.add_argument("--jax-cache-dir", default=d.jax_cache_dir,
                   help="persistent XLA compilation cache directory; the "
                        "~seconds cold compile of the solver programs is "
                        "then paid once per image instead of per process "
                        "restart (empty = no persistent cache)")
    p.add_argument("--leader-elect", type=_bool, default=False,
                   help="Lease-based leader election so only one replica "
                        "acts (restores what reference rescheduler.go:139 "
                        "removed); kube cluster mode only")
    p.add_argument("--leader-elect-namespace", default="kube-system")
    p.add_argument("--leader-elect-identity", default="",
                   help="holder identity (default: hostname_pid_rand)")
    p.add_argument("--leader-elect-lease-duration", default="15s",
                   help="takeover after the holder is quiet this long")
    p.add_argument("--watch-cache", type=_bool, default=True,
                   help="serve per-tick reads from watch-backed caches "
                        "(the reference's lister behavior) instead of "
                        "polling LISTs; kube cluster mode only")
    p.add_argument("--cluster", default="synthetic:1",
                   help="cluster source: synthetic:<config#>[:seed] (demo/bench), "
                        "kube (apiserver from kubeconfig/in-cluster creds), or "
                        "kube:<url> (explicit apiserver URL)")
    p.add_argument("--ticks", type=int, default=0,
                   help="run N housekeeping ticks then exit (0 = forever)")
    p.add_argument("--no-metrics-server", action="store_true")
    p.add_argument("--trace-dir", default="",
                   help="write jax.profiler traces of solver phases here")
    return p


def _bool(s: str) -> bool:
    return str(s).lower() in ("1", "true", "yes")


def start_watch_client(client, config: "ReschedulerConfig", clock):
    """Wrap ``client`` in the watch-backed cache layer and sync it.

    Graceful startup degradation: if the caches fail to sync (apiserver
    flaky at boot, watch endpoints unreachable), the process does NOT
    die — it logs a warning, marks the loop degraded (sticky on
    /healthz and the ``rescheduler_degraded`` gauge), and falls back to
    the polling client, whose per-tick LISTs need no warm-up. A
    rescheduler that cannot watch can still reschedule; it just pays
    the LIST cost the watch path exists to avoid."""
    from k8s_spot_rescheduler_tpu.io.watch import WatchingKubeClusterClient
    from k8s_spot_rescheduler_tpu.loop import health

    wc = WatchingKubeClusterClient(
        client,
        clock=clock,
        progress_deadline=config.watch_progress_deadline,
    )
    try:
        wc.start()
        return wc
    except Exception as err:  # noqa: BLE001 — degrade, don't die
        log.error(
            "Watch caches failed to sync (%s); falling back to the "
            "polling client — degraded (per-tick LISTs) until restart",
            err,
        )
        wc.stop()
        health.STATE.note_startup_degraded()
        return client


def config_from_args(args) -> ReschedulerConfig:
    return ReschedulerConfig(
        running_in_cluster=args.running_in_cluster,
        namespace=args.namespace,
        housekeeping_interval=parse_duration(args.housekeeping_interval),
        node_drain_delay=parse_duration(args.node_drain_delay),
        pod_eviction_timeout=parse_duration(args.pod_eviction_timeout),
        max_graceful_termination=parse_duration(args.max_graceful_termination),
        listen_address=args.listen_address,
        kubeconfig=args.kubeconfig,
        delete_non_replicated_pods=args.delete_non_replicated_pods,
        on_demand_node_label=args.on_demand_node_label,
        spot_node_label=args.spot_node_label,
        priority_threshold=args.priority_threshold,
        eviction_retry_time=parse_duration(args.eviction_retry_time),
        max_pods_per_node_hint=args.max_pods_per_node_hint,
        max_drains_per_tick=args.max_drains_per_tick,
        fallback_best_fit=args.fallback_best_fit,
        use_columnar=args.use_columnar,
        solver=args.solver,
        repair_rounds=args.repair_rounds,
        auto_shard=args.auto_shard,
        solver_hbm_budget=args.solver_hbm_budget,
        carry_chunks=args.carry_chunks,
        incremental_device_cache=args.incremental_device_cache,
        staged_chunk_lanes=args.staged_chunk_lanes,
        staged_early_exit=args.staged_early_exit,
        plan_schedule_enabled=args.plan_schedule_enabled,
        schedule_horizon=args.schedule_horizon,
        jax_cache_dir=args.jax_cache_dir,
        planner_url=args.planner_url,
        planner_urls=args.planner_urls,
        planner_timeout=parse_duration(args.planner_timeout),
        delta_wire_enabled=args.delta_wire_enabled,
        service_batch_window=parse_duration(args.service_batch_window),
        service_queue_timeout=parse_duration(args.service_queue_timeout),
        service_resync_ingest_cap=args.service_resync_ingest_cap,
        service_resync_ingest_budget=args.service_resync_ingest_budget,
        device_sick_threshold=args.device_sick_threshold,
        service_drain_grace=parse_duration(args.service_drain_grace),
        service_state_dir=args.service_state_dir,
        service_chaos_profile=args.service_chaos_profile,
        service_chaos_seed=args.service_chaos_seed,
        kube_retry_max=args.kube_retry_max,
        kube_retry_base=args.kube_retry_base,
        breaker_threshold=args.breaker_threshold,
        breaker_max_interval=parse_duration(args.breaker_max_interval),
        reconcile_orphaned_taints=args.reconcile_orphaned_taints,
        chaos_profile=args.chaos_profile,
        chaos_seed=args.chaos_seed,
        chaos_watch_stall_rate=args.chaos_watch_stall_rate,
        watch_progress_deadline=parse_duration(args.watch_progress_deadline),
        mirror_staleness_budget=parse_duration(args.mirror_staleness_budget),
        resync_interval=parse_duration(args.resync_interval),
        trace_enabled=args.trace_enabled,
        flight_ring_size=args.flight_ring_size,
        flight_dump_dir=args.flight_dump_dir,
        debug_endpoints=args.debug_endpoints,
        resources=tuple(r for r in args.resources.split(",") if r),
        mesh_shape=(
            tuple(int(x) for x in args.mesh_shape.lower().split("x"))
            if args.mesh_shape
            else (1, 1)
        ),
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        print(f"k8s-spot-rescheduler-tpu {VERSION}")
        return 0

    log.setup(args.verbosity)
    try:
        config = config_from_args(args)
    except (LabelFormatError, ValueError) as err:
        print(f"Error: {err}", file=sys.stderr)
        return 1

    if args.serve:
        # service mode: no control loop, no cluster client — one shared
        # TPU planner serving a fleet of --planner-url agents
        from k8s_spot_rescheduler_tpu.service.server import (
            ServiceServer,
            install_sigterm_drain,
        )

        if not args.no_metrics_server:
            from k8s_spot_rescheduler_tpu.metrics import registry as metrics

            metrics.serve(config.listen_address)
        log.info("Running planner service")
        server = ServiceServer(config, args.serve)
        # SIGTERM = graceful drain: stop admitting, finish queued
        # batches within service_drain_grace, persist warm state, exit
        install_sigterm_drain(server)
        server.serve_forever()
        return 0

    log.info("Running Rescheduler")
    if args.trace_dir:
        from k8s_spot_rescheduler_tpu.utils import tracing

        tracing.enable_profiler(args.trace_dir)
    if not args.no_metrics_server:
        from k8s_spot_rescheduler_tpu.metrics import registry as metrics

        metrics.serve(config.listen_address)

    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.clock import RealClock

    def chaos_wrap(c, clk):
        import dataclasses

        from k8s_spot_rescheduler_tpu.io.chaos import (
            ChaosClusterClient,
            FaultPlan,
        )

        log.info(
            "CHAOS: fault injection enabled (profile=%s seed=%d) — "
            "testing mode, not production",
            config.chaos_profile, config.chaos_seed,
        )
        plan = FaultPlan.profile(config.chaos_profile, config.chaos_seed)
        if config.chaos_watch_stall_rate > 0:
            plan = dataclasses.replace(
                plan, watch_stall_rate=config.chaos_watch_stall_rate
            )
        return ChaosClusterClient(c, plan, clock=clk)

    elector = None
    if args.cluster.startswith("synthetic:"):
        from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster

        parts = args.cluster.split(":")
        try:
            spec = CONFIGS[int(parts[1])]
            seed = int(parts[2]) if len(parts) > 2 else 0
        except (KeyError, ValueError, IndexError):
            print(
                f"Error: unknown synthetic config {args.cluster!r} "
                f"(available: {sorted(CONFIGS)})",
                file=sys.stderr,
            )
            return 1
        log.info("Generating synthetic cluster %s (seed %d)", spec.name, seed)
        client = generate_cluster(spec, seed, reschedule_evicted=True)
        # the demo always runs on the fake cluster's virtual clock — pod
        # termination timers live on it
        clock = client.clock
        if config.chaos_profile:
            client = chaos_wrap(client, clock)
        recorder = client
    elif args.cluster == "kube" or args.cluster.startswith("kube:"):
        from k8s_spot_rescheduler_tpu.io.kube import (
            KubeClusterClient,
            from_environment,
        )

        try:
            if args.cluster.startswith("kube:"):
                # explicit apiserver URL (e.g. kube:http://127.0.0.1:8080)
                client = KubeClusterClient(args.cluster.split(":", 1)[1])
            else:
                client = from_environment(
                    config.running_in_cluster, config.kubeconfig
                )
        except Exception as err:  # noqa: BLE001
            print(f"Error: failed to create kube client: {err}", file=sys.stderr)
            return 1
        # transient-read retry policy (io/kube.py backoff loop)
        client.retry_max = config.kube_retry_max
        client.retry_base = config.kube_retry_base
        from k8s_spot_rescheduler_tpu.io import native_ingest

        # the native LIST decoder only carries the standard resources;
        # exotic --resources must flow through the Python decoders
        client.use_native_ingest = native_ingest.supports(config.resources)
        clock = RealClock()
        if config.chaos_profile:
            # wrapped UNDER the watch cache (below), so the watch
            # threads' streams traverse the chaos _stream hook (drop
            # injection) and writes/get_pod are faulted; the lease
            # elector's _request plumbing passes through untouched
            client = chaos_wrap(client, clock)
        if args.leader_elect:
            from k8s_spot_rescheduler_tpu.io.lease import LeaseElector

            elector = LeaseElector(
                client,
                identity=args.leader_elect_identity,
                namespace=args.leader_elect_namespace,
                lease_duration=parse_duration(
                    args.leader_elect_lease_duration
                ),
            )
            # renew off-loop so a long drain never lets the lease lapse
            elector.start_background()
        if args.watch_cache:
            client = start_watch_client(client, config, clock)
        recorder = client
    else:
        print(f"Error: unknown --cluster {args.cluster!r}", file=sys.stderr)
        return 1

    try:
        if config.planner_url or config.planner_urls:
            # agent mode: the solve crosses the wire to a shared
            # planner service (failover list supported); everything
            # else stays local
            from k8s_spot_rescheduler_tpu.service.agent import RemotePlanner

            planner = RemotePlanner(config)
        else:
            planner = SolverPlanner(config)
    except ValueError as err:
        print(f"Error: {err}", file=sys.stderr)
        return 1
    r = Rescheduler(
        client, planner, config, clock=clock, recorder=recorder,
        # HA: a follower must not perform the startup taint sweep — it
        # could untaint the LEADER's in-flight drain; the per-tick sweep
        # runs once this replica is leader-gated into ticking
        startup_sweep=(elector is None or elector.is_leader),
        # taint-ownership holder id (defaults to the hostname — stable
        # across a restart of the same replica, distinct between HA
        # replicas); an explicit lease identity overrides it
        identity=args.leader_elect_identity or None,
    )
    ticks = 0
    while args.ticks == 0 or ticks < args.ticks:
        # breaker-widened while consecutive observe errors persist
        clock.sleep(r.effective_interval())
        # a follower's skipped interval still counts toward --ticks so
        # bounded runs terminate whoever holds the lease
        ticks += 1
        if elector is not None and not elector.is_leader and not elector.ensure():
            log.vlog(2, "not the leader; standing by")
            continue
        result = r.tick()
        if result.drained or result.drain_failed:
            log.info(
                "tick %d: drained=%s failed=%s", ticks,
                result.drained, result.drain_failed,
            )
        elif result.report is not None:
            log.info(
                "tick %d: %d candidates, %d feasible, solve %.1f ms",
                ticks, result.report.n_candidates, result.report.n_feasible,
                result.report.solve_seconds * 1e3,
            )
        else:
            log.info("tick %d: skipped (%s)", ticks, result.skipped)
    return 0


if __name__ == "__main__":
    sys.exit(main())
