"""Process entry point."""

from k8s_spot_rescheduler_tpu.cli.main import build_parser, main

__all__ = ["build_parser", "main"]
