"""Prometheus metrics.

The reference's four series under namespace ``spot_rescheduler``
(reference metrics/metrics.go:28-64), reproduced name-for-name and
label-for-label, plus TPU-native solver telemetry. Served over HTTP at the
configured listen address like the reference's promhttp handler
(rescheduler.go:126-130).

Reference update points this module mirrors:
- nodes count per tick            rescheduler.go:202 → UpdateNodesMap
- pods per on-demand node         rescheduler.go:259
- pods per spot node              rescheduler.go:396
- drain success/failure counter   rescheduler.go:377-382
- evictions counter               scaler/scaler.go:108
"""

from __future__ import annotations

import math
from collections import OrderedDict, deque

from prometheus_client import Counter, Gauge, Histogram, start_http_server

NAMESPACE = "spot_rescheduler"

node_pods_count = Gauge(
    "node_pods_count",
    "Number of pods on each node.",
    ["node_type", "node"],
    namespace=NAMESPACE,
)

nodes_count = Gauge(
    "nodes_count",
    "Number of nodes in cluster.",
    ["node_type"],
    namespace=NAMESPACE,
)

node_drain_count = Counter(
    "node_drain_total",
    "Number of nodes drained by rescheduler.",
    ["drain_state", "node"],
    namespace=NAMESPACE,
)

evictions_count = Counter(
    "evicted_pods_total",
    "Number of pods evicted by the rescheduler.",
    namespace=NAMESPACE,
)

# --- TPU-native additions (no reference equivalent) ---

plan_duration = Histogram(
    "plan_duration_seconds",
    "Wall time of one drain-plan solve on the accelerator.",
    ["solver"],
    namespace=NAMESPACE,
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.2, 0.5, 1.0, 5.0),
)

plan_candidates = Gauge(
    "plan_candidates",
    "Candidate on-demand nodes evaluated in the last solve.",
    namespace=NAMESPACE,
)

# Conservatism observability (VERDICT round-2 task 4): the planner's
# safe-direction over-approximations can silently pin the controller at
# zero drains (one unmodeled-constraint pod per on-demand node is enough).
# These series tell the operator WHY no drain happened — the reference
# only logs the blocking pod per node (rescheduler.go:232-238).

unplaceable_pods = Gauge(
    "unplaceable_pods",
    "Evictable pods on candidate nodes whose scheduling constraints the "
    "planner does not model (treated as placeable nowhere; such a pod's "
    "node can never be proven drainable).",
    namespace=NAMESPACE,
)

blocked_candidates = Gauge(
    "blocked_candidates",
    "Candidate on-demand nodes whose drain could not be approved this "
    "tick, by reason: unmodeled (carries an unplaceable pod), pdb "
    "(disruption budget exhausted), non-replicated (bare pod without "
    "--delete-non-replicated-pods), no-capacity (solver proved no "
    "predicate-valid placement exists).",
    ["reason"],
    namespace=NAMESPACE,
)

BLOCKED_REASONS = ("unmodeled", "pdb", "non-replicated", "no-capacity")

# Solver-mode observability (VERDICT round-4 weak #2): the auto-shard
# reroute silently swaps the running program past the single-chip HBM
# estimate, and that program has no repair phase — quality can degrade
# with nothing for an operator to alarm on. Exactly one
# (configured, running) pair reads 1 at any time.

solver_mode = Gauge(
    "solver_mode",
    "1 for the (configured, running) solver pair of the last solve; the "
    "running label differs from the configured one while the auto-shard "
    "reroute is engaged (problem exceeds the single-chip HBM budget).",
    ["configured", "running"],
    namespace=NAMESPACE,
)

repair_unavailable = Gauge(
    "repair_unavailable",
    "1 while the last solve ran WITHOUT the repair phase the config "
    "asked for (only the 2-D cand×spot tier drops it, past even the "
    "spot-CHUNKED repair ceiling — the cand-only tier keeps repair, "
    "chunked when one lane block's unchunked state no longer fits a "
    "device) — drains in the contended regimes repair exists for may "
    "be missed; alarm on this to catch degraded-quality mode.",
    namespace=NAMESPACE,
)

solver_repair_chunks = Gauge(
    "solver_repair_chunks",
    "Spot chunks the repair phase of the last solve ran with: 1 = the "
    "unchunked single-sweep search, >1 = the elect-then-commit "
    "spot-chunked search (per-lane repair state exceeded one device's "
    "budget; solver/repair.plan_repair_chunked), 0 = repair did not "
    "run (disabled by config, or dropped on the 2-D tier past the "
    "chunked ceiling — repair_unavailable distinguishes the two).",
    namespace=NAMESPACE,
)

solver_carry_chunks = Gauge(
    "solver_carry_chunks",
    "Carry chunks of the last solve's carry-streamed tier (solver/"
    "fallback.with_repair_streamed): the spot axis streams through the "
    "greedy scans in this many ordered chunks with narrow delta "
    "carries. 0 = a wide-carry tier ran (single-chip, cand-sharded, "
    "cand-chunked or 2-D).",
    namespace=NAMESPACE,
)

solver_carry_bytes = Gauge(
    "solver_carry_bytes",
    "Estimated per-device resident scan-carry bytes of the last "
    "dispatched solver program (the 'carries' component of solver/"
    "memory.estimate_union_hbm_breakdown at the dispatched tier's "
    "layout — narrow delta planes on the carry-streamed tier). The "
    "per-spot resident carry the 20x scaling ceiling is set by.",
    namespace=NAMESPACE,
)

tick_phase_duration = Histogram(
    "tick_phase_duration_seconds",
    "Wall time of each housekeeping-tick phase (observe / plan-dispatch "
    "/ observe-metrics / plan-fetch / actuate, plus the aggregate plan "
    "phase; observe-metrics overlaps the in-flight device solve).",
    ["phase"],
    namespace=NAMESPACE,
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0),
)

# Incremental-tick observability (device-resident pipeline): how much of
# the per-tick host↔device traffic and solve compute the delta-pack and
# staged early-exit paths actually saved — and how often the cache missed.

solver_delta_pack_lanes = Gauge(
    "solver_delta_pack_lanes",
    "Changed candidate lanes the last tick's delta-pack applied to the "
    "device-resident problem tensors (0 = nothing changed; the gauge is "
    "untouched on full-repack ticks).",
    namespace=NAMESPACE,
)

solver_full_repack = Counter(
    "solver_full_repack",
    "Ticks that re-uploaded the whole packed problem instead of a delta "
    "(cold cache, shape growth past the high-water pads, or a failed "
    "delta apply).",
    namespace=NAMESPACE,
)

solver_delta_upload_bytes = Gauge(
    "solver_delta_upload_bytes",
    "Host-to-device bytes the last tick actually shipped (padded delta, "
    "or the full problem on repack ticks).",
    namespace=NAMESPACE,
)

solver_chunks_solved = Gauge(
    "solver_chunks_solved",
    "Candidate-lane chunks the staged solver actually solved last tick.",
    namespace=NAMESPACE,
)

solver_chunks_skipped = Gauge(
    "solver_chunks_skipped",
    "Candidate-lane chunks skipped last tick (prefilter-eliminated or "
    "beyond the first feasible chunk under early exit).",
    namespace=NAMESPACE,
)


# Robustness observability (chaos-hardened control plane): the loop's
# graceful-degradation paths must be visible, or an operator cannot tell
# a healthy-quiet controller from one silently limping on fallbacks.

kube_request_retries = Counter(
    "kube_request_retries",
    "Transient kube API read failures (HTTP 429/5xx, connection "
    "reset/timeout) that were retried with jittered exponential backoff "
    "(io/kube.py read verbs only; writes are single-attempt by design).",
    namespace=NAMESPACE,
)

kube_request_failures = Counter(
    "kube_request_failures",
    "Kube API reads that exhausted the transient-retry budget and "
    "surfaced their error to the caller (the tick then skips under the "
    "observe-error policy).",
    namespace=NAMESPACE,
)

planner_fallback = Counter(
    "planner_fallback",
    "Ticks whose configured planner raised and were degraded to the CPU "
    "numpy-oracle fallback planner instead of crashing the loop "
    "(loop/controller.py; /healthz reports degraded:true while this is "
    "the latest tick's state).",
    namespace=NAMESPACE,
)

orphaned_taints_recovered = Counter(
    "orphaned_taints_recovered",
    "Orphaned ToBeDeleted taints removed by the crash-recovery sweep: "
    "taints no active drain owns, left by a drain interrupted between "
    "taint and cleanup (the reference leaves these for CA to collect).",
    namespace=NAMESPACE,
)

rescheduler_degraded = Gauge(
    "rescheduler_degraded",
    "1 while the control loop is degraded: the last completed tick ran "
    "on the fallback planner, the observe-error circuit breaker is "
    "engaged (consecutive failed ticks past the threshold widened the "
    "housekeeping interval), the watch mirror is staler than the "
    "freshness budget, or the watch caches failed to sync at startup "
    "and the loop fell back to polling LISTs.",
    namespace=NAMESPACE,
)


# Watch-liveness / freshness observability (freshness-gated observe path,
# docs/ROBUSTNESS.md): the watch mirror is only trustworthy because these
# series prove it — a wedged-open stream, a drifted mirror, or a tick
# planned from stale data must each be visible, not inferred from logs.

watch_events = Counter(
    "watch_events",
    "Object events (ADDED/MODIFIED/DELETED) applied to a watch cache "
    "(io/watch.py; BOOKMARKs advance the resourceVersion without "
    "counting here).",
    ["resource"],
    namespace=NAMESPACE,
)

watch_relists = Counter(
    "watch_relists",
    "Full re-LISTs a watcher performed: the seeding LIST, 410-Gone "
    "recovery, and post-error reconciliation (the anti-entropy audit's "
    "LIST counts under resync_audits instead).",
    ["resource"],
    namespace=NAMESPACE,
)

watch_stream_errors = Counter(
    "watch_stream_errors",
    "Watch streams that died with a transport/protocol error and were "
    "reconnected after a backed-off re-LIST (progress-deadline stalls "
    "count under watch_stalls instead).",
    ["resource"],
    namespace=NAMESPACE,
)

watch_stalls = Counter(
    "watch_stalls",
    "Watch streams killed by the client-side progress deadline: open "
    "but silent past watch_progress_deadline (no event, no bookmark, "
    "no server close). The stream reconnects from its last "
    "resourceVersion without a re-LIST — the version is still valid; "
    "nothing was missed, the transport was just wedged.",
    ["resource"],
    namespace=NAMESPACE,
)

watch_drift = Counter(
    "watch_drift",
    "Objects the anti-entropy resync audit found FIELD-LEVEL diverged "
    "between a fresh LIST and the incremental watch mirror: present on "
    "both sides, untouched by the stream across the audit window, yet "
    "carrying different content. Any increment forces a store replace "
    "+ full repack and emits a WatchDriftHealed event — drift is never "
    "silent. Alarm on a sustained rate: it means the watch protocol or "
    "the mirror is corrupting or dropping updates (a lone increment "
    "can be a MODIFIED still in flight at the LIST instant).",
    ["resource"],
    namespace=NAMESPACE,
)

watch_presence_heals = Counter(
    "watch_presence_heals",
    "Objects the audit added or removed to re-sync mirror PRESENCE "
    "with a fresh LIST (missing or phantom entries). Usually an "
    "ADDED/DELETED event still in flight when the LIST was issued — "
    "ordinary lag, healed by the same store replace but kept apart "
    "from the alarm-grade watch_drift series so routine churn does "
    "not page anyone.",
    ["resource"],
    namespace=NAMESPACE,
)

resync_audits = Counter(
    "resync_audits",
    "Completed anti-entropy audits: one background LIST per resource "
    "diffed field-by-field against the watch mirror, every "
    "resync_interval. A clean audit also re-proves mirror freshness "
    "(the mirror equals a fresh LIST by construction).",
    namespace=NAMESPACE,
)

mirror_staleness = Gauge(
    "mirror_staleness_seconds",
    "Age of the watch mirror at the last tick's freshness gate: wall "
    "seconds since every watch stream last proved progress (event, "
    "bookmark, clean server close, successful re-LIST, or clean "
    "audit). Past mirror_staleness_budget the tick refuses to plan "
    "from the mirror.",
    namespace=NAMESPACE,
)

freshness_bypass = Counter(
    "freshness_bypass",
    "Ticks whose freshness gate found the watch mirror staler than "
    "mirror_staleness_budget and degraded the observe path to a "
    "direct apiserver LIST, bypassing the sick cache (first rung of "
    "the degradation ladder; the second is skip-tick + the circuit "
    "breaker when no direct path exists or it too fails).",
    namespace=NAMESPACE,
)

mirror_stale_planned = Counter(
    "mirror_stale_planned",
    "Ticks the last-line freshness guard caught about to PLAN from a "
    "mirror that aged past mirror_staleness_budget between the gate "
    "and the plan dispatch — the tick is skipped instead, so no "
    "eviction is ever planned from over-budget data. Structurally "
    "zero in healthy operation; any nonzero value means the gate was "
    "outrun and must be alarmed on.",
    namespace=NAMESPACE,
)

observe_delta_events = Gauge(
    "observe_delta_events",
    "Watch deltas drained into the columnar mirror at the last tick's "
    "freeze (0 on a quiet cluster — the observe+pack path is then a "
    "cache hit; the full LIST survives only as the anti-entropy "
    "audit).",
    namespace=NAMESPACE,
)


# Multi-tenant planner-service observability (service/server.py +
# service/agent.py): one TPU planning for a fleet means the batching
# queue, the shared solves and the agents' degradation paths each need
# their own series — a starved tenant or a silently-falling-back agent
# must be visible on a dashboard, not inferred from latency.

service_requests = Counter(
    "service_requests",
    "Plan requests the planner service accepted or refused, by outcome: "
    "ok (planned in a batch), rejected (depth/body caps before the body "
    "was read), expired (waited past the queue timeout and was evicted "
    "with 503 + Retry-After), error (decode or solve failure).",
    ["outcome"],
    namespace=NAMESPACE,
)

service_batch_lanes = Gauge(
    "service_batch_lanes",
    "Candidate lanes in the last batched solve, summed across the "
    "tenant lane-blocks that shared it (the co-batching proof: a value "
    "above any single tenant's lane count means unrelated clusters "
    "amortized one compile and one device dispatch).",
    namespace=NAMESPACE,
)

service_batch_tenants = Gauge(
    "service_batch_tenants",
    "Tenant lane-blocks sharing the last batched solve (1 = the batch "
    "window closed with a lone tenant; the fleet-scale win is this "
    "sitting near the HBM-derived batch cap).",
    namespace=NAMESPACE,
)

service_queue_wait_ms = Histogram(
    "service_queue_wait_ms",
    "Milliseconds a plan request spent in the tenant queue before its "
    "batch dispatched (the fairness SLO: bounded by one batch interval "
    "per deficit-round-robin design, regardless of other tenants' "
    "flooding).",
    namespace=NAMESPACE,
    buckets=(1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
             5000.0, 30000.0),
)

service_tenant_evictions = Counter(
    "service_tenant_evictions",
    "Plan requests evicted from the service queue after waiting past "
    "the queue timeout (answered 503 + Retry-After derived from the "
    "measured batch cadence), per tenant — a single tenant's label "
    "climbing means ITS submission rate, not the service, is the "
    "problem (DRR protects the others).",
    ["tenant"],
    namespace=NAMESPACE,
)

remote_planner_fallback = Counter(
    "remote_planner_fallback",
    "Agent ticks planned by the LOCAL numpy-oracle fallback because "
    "EVERY configured planner endpoint was unreachable, overloaded, "
    "breaker-open, or answered out of protocol (service/agent.py "
    "RemotePlanner; per-endpoint breakers skip a failing replica for a "
    "backoff window and re-engage on the next healthy reply).",
    namespace=NAMESPACE,
)

remote_planner_failover = Counter(
    "remote_planner_failover",
    "Agent ticks served by a planner endpoint AFTER at least one "
    "earlier endpoint in the ordered --planner-urls list failed or was "
    "breaker-open this tick — full-fidelity remote plans, but the "
    "primary replica is unhealthy (flight recorder kind: failover).",
    namespace=NAMESPACE,
)

remote_wire_connection_reuse = Counter(
    "remote_wire_connection_reuse",
    "Agent plan requests served over an ALREADY-ESTABLISHED pooled "
    "keep-alive connection (service/agent.py PooledWireTransport) — "
    "the per-tick TCP+HTTP setup tax the persistent wire amortizes "
    "away. Steady state this grows by 1 per tick per endpoint; "
    "serve-smoke asserts >= ticks-1 over a live ServiceServer.",
    namespace=NAMESPACE,
)

remote_wire_reconnects = Counter(
    "remote_wire_reconnects",
    "Pooled keep-alive sockets found stale/half-closed (server "
    "restart, idle timeout, LB reset) and transparently replaced by "
    "ONE retry on a fresh connection before the request counted "
    "against the endpoint's breaker (service/agent.py stale-retry "
    "contract, docs/ROBUSTNESS.md). A steadily climbing rate means "
    "something on the path kills idle connections faster than the "
    "tick cadence.",
    namespace=NAMESPACE,
)

service_delta_requests = Counter(
    "service_delta_requests",
    "Delta-shipping plan requests (wire v4 KIND_PACKED_DELTA) by "
    "outcome: applied (the base fingerprint matched the cached tenant "
    "state and the churn scattered into it before the batch solve), "
    "resync (the service demanded one full-pack resync — restart, "
    "cache eviction, fingerprint mismatch, or any decode/apply "
    "anomaly; the agent's next upload is a full pack, never a wrong "
    "plan). Flight recorder kind: delta-resync, same sites.",
    ["outcome"],
    namespace=NAMESPACE,
)

service_wire_ingest_bytes = Counter(
    "service_wire_ingest_bytes",
    "Request-body bytes the planner service ingested on /v2/plan "
    "(full packs and deltas alike) — the fleet-scale ceiling the delta "
    "wire exists to lower: steady state this grows O(churn) per tick "
    "per tenant, with full-pack-sized jumps only on first contact and "
    "forced resyncs (serve-smoke asserts it).",
    namespace=NAMESPACE,
)

service_tenant_cache = Gauge(
    "service_tenant_cache_entries",
    "Tenants with device/host-resident packed state cached for the "
    "delta wire (pruned with the tenant-state TTL and hard-capped; an "
    "evicted tenant's next delta is answered with a resync demand).",
    namespace=NAMESPACE,
)

service_admission_shed = Counter(
    "service_admission_shed",
    "Plan requests the planner service shed, labeled by the admission "
    "edge that refused them: max-inflight (the handler depth cap "
    "answered 503 before the body was read), queue-timeout (evicted "
    "after waiting a full service_queue_timeout in the tenant queue), "
    "deadline (evicted after waiting out the CLIENT's declared "
    "X-Planner-Deadline, shorter than the queue timeout), drain-refuse "
    "(a draining replica refused pre-body), drain-evict (queued work "
    "evicted when the drain grace expired), resync-storm (a full-pack "
    "resync ingest refused by the bounded resync admission class — "
    "concurrent-ingest cap or byte ledger — with a load-derived "
    "Retry-After). Each reason fires from exactly ONE site, paired "
    "with a flight shed event ('service-shed', or 'resync-shed' for "
    "resync-storm) carrying the same reason attr — the capacity "
    "curve's shed axis.",
    ["reason"],
    namespace=NAMESPACE,
)

# The canonical admission-shed label set — every reason the counter
# above can ever carry, in one importable place. bench/fleet_twin.py's
# induce_shed_edges() enumerates THIS tuple (never its own literal), so
# adding a reason here without an induction recipe turns the fleet
# smoke red instead of letting the new edge go silently unexercised.
SHED_REASONS = (
    "max-inflight",
    "queue-timeout",
    "deadline",
    "drain-refuse",
    "drain-evict",
    "resync-storm",
)

service_resync_ingest_admitted = Counter(
    "service_resync_ingest_admitted",
    "Full-pack resync ingests ADMITTED through the bounded resync "
    "admission class (a fingerprinted full pack for a tenant with no "
    "cached state — first contact or post-restart re-seed). Refusals "
    "land in service_admission_shed{reason=resync-storm}; together the "
    "two count every resync-class arrival.",
    namespace=NAMESPACE,
)

service_resync_ingest_inflight = Gauge(
    "service_resync_ingest_inflight",
    "Full-pack resync ingests currently holding an admission token "
    "(decode through batch solve and cache seed). The restart-storm "
    "bench asserts the run high-water of this gauge never exceeds "
    "service_resync_ingest_cap — the shed-not-collapse contract.",
    namespace=NAMESPACE,
)

service_resync_ingest_ledger = Gauge(
    "service_resync_ingest_ledger_bytes",
    "Estimated HBM bytes (per-tenant bucket footprint, the same "
    "estimate_union_hbm_breakdown model the batch cap uses) committed "
    "by in-flight resync ingests — the byte-budgeted ledger that "
    "bounds how much cache-seeding state a correlated storm can "
    "commit concurrently.",
    namespace=NAMESPACE,
)

service_bucket_compile_hits = Counter(
    "service_bucket_compile_hits",
    "Batched solves whose stacked shape family (bucket dims x tenant "
    "axis x schedule horizon) had already been solved by this process "
    "— the jit program was reused, no compile was paid. The "
    "compile-sharing win of power-of-two shape buckets: hits/(hits+"
    "misses) is the fleet's compile hit rate as tenant shapes drift.",
    namespace=NAMESPACE,
)

service_bucket_compile_misses = Counter(
    "service_bucket_compile_misses",
    "Batched solves that were the FIRST of their stacked shape family "
    "in this process — each paid (or would pay, on a device backend) "
    "one jit compile. Climbing misses under a stable fleet means "
    "tenant shape drift is walking out of the bucketed shape space "
    "(docs/DESIGN.md service era: buckets exist to bound this).",
    namespace=NAMESPACE,
)

service_batch_occupancy = Gauge(
    "service_batch_occupancy",
    "Tenant lane-blocks in the last batched solve as a fraction of the "
    "HBM-derived batch cap for its bucket (1.0 = the batch dispatched "
    "full; the saturation gauge the capacity curve sweeps — queue "
    "waits stay flat until this pins near 1, then the knee).",
    namespace=NAMESPACE,
)

service_queue_wait_p50 = Gauge(
    "service_queue_wait_p50_ms",
    "Median queue wait over the recent window (the bounded ring behind "
    "service_tenant_wait_snapshot, all tenants pooled) — unlike the "
    "cumulative service_queue_wait_ms histogram this answers 'how is "
    "the fleet RIGHT NOW', and resets with the window.",
    namespace=NAMESPACE,
)

service_queue_wait_p99 = Gauge(
    "service_queue_wait_p99_ms",
    "p99 queue wait over the recent window (same ring as the p50 "
    "gauge) — the tail the capacity-planning SLO is declared against: "
    "tenants/device at a given occupancy is read off where this "
    "crosses the SLO.",
    namespace=NAMESPACE,
)

service_device_sick = Gauge(
    "service_device_sick",
    "1 while the planner service's device-health watchdog "
    "(service/devhealth.py) holds the accelerator SICK — consecutive "
    "slower-than-baseline batched solves, a canary timeout, or an XLA "
    "error — and every batch is served by the numpy-oracle host path; "
    "flips back only after hysteresis recovery probes pass. The "
    "/healthz 'device' field and the flight recorder's device-sick "
    "event are driven by the same edge.",
    namespace=NAMESPACE,
)


# Drain-schedule observability (solver/schedule.py + planner/schedule.py
# + loop/controller.py): one device fetch returns a whole drain schedule;
# the controller executes it across ticks with per-step from-scratch
# validation. The invalidation counter is the degradation edge — churn
# broke a prediction and cost a re-plan fetch (never a wrong eviction).

plan_schedule_len = Gauge(
    "plan_schedule_len",
    "Drain steps in the last cut drain-to-exhaustion schedule (one "
    "device fetch covers this many drains; 0 = the last cut found no "
    "drainable candidate).",
    namespace=NAMESPACE,
)

schedule_invalidated = Counter(
    "schedule_invalidated",
    "Drain-schedule tails invalidated before execution: the live "
    "mirror no longer matched the schedule's predicted state (cluster "
    "churn since the cut) or a step failed its from-scratch placement "
    "re-proof, so the remaining steps were discarded and the tick "
    "re-planned fresh. Each increment costs one extra planner fetch "
    "and loses no correctness; a sustained rate means the cluster "
    "churns faster than schedule_horizon drains and the horizon "
    "should shrink (flight recorder kind: schedule-invalidated).",
    namespace=NAMESPACE,
)


def update_nodes_map(on_demand_label: str, spot_label: str, n_on_demand: int, n_spot: int) -> None:
    """reference metrics/metrics.go:73-80 (labels carry the configured
    node-class label strings, as in the reference)."""
    nodes_count.labels(on_demand_label).set(n_on_demand)
    nodes_count.labels(spot_label).set(n_spot)


def update_node_pods_count(node_type: str, node_name: str, num_pods: int) -> None:
    node_pods_count.labels(node_type, node_name).set(num_pods)


def update_evictions_count() -> None:
    evictions_count.inc()


def update_node_drain_count(state: str, node_name: str) -> None:
    node_drain_count.labels(state, node_name).inc()


def observe_plan_duration(solver: str, seconds: float, candidates: int) -> None:
    plan_duration.labels(solver).observe(seconds)
    plan_candidates.set(candidates)


def observe_tick_phase(phase: str, seconds: float) -> None:
    tick_phase_duration.labels(phase).observe(seconds)


_last_solver_mode = [None]  # (configured, running) of the previous solve


def update_solver_mode(
    configured: str,
    running: str,
    repair_dropped: bool,
    repair_chunks: int | None = None,
    carry_chunks: int | None = None,
    carry_bytes: int | None = None,
) -> None:
    """Expose what the last solve actually ran. The previous label pair
    is zeroed (not removed) so dashboards see a clean 1-of-N encoding
    and the flip to/from the reroute is a visible edge.
    ``repair_chunks`` mirrors the dispatch decision's spot-chunk count
    into ``solver_repair_chunks`` (None leaves the gauge untouched);
    ``carry_chunks``/``carry_bytes`` mirror the carry-streamed tier's
    chunk count and estimated resident carry bytes into
    ``solver_carry_chunks``/``solver_carry_bytes`` (None / negative
    carry_bytes leave the gauges untouched)."""
    prev = _last_solver_mode[0]
    if prev is not None and prev != (configured, running):
        solver_mode.labels(*prev).set(0)
    solver_mode.labels(configured, running).set(1)
    _last_solver_mode[0] = (configured, running)
    repair_unavailable.set(1 if repair_dropped else 0)
    if repair_chunks is not None:
        solver_repair_chunks.set(repair_chunks)
    if carry_chunks is not None:
        solver_carry_chunks.set(carry_chunks)
    if carry_bytes is not None and carry_bytes >= 0:
        solver_carry_bytes.set(carry_bytes)


def update_incremental_tick(report) -> None:
    """Mirror one PlanReport's incremental-pipeline telemetry into the
    gauges above (called by the control loop after each plan)."""
    if report.full_repack:
        solver_full_repack.inc()
    elif report.delta_pack_lanes >= 0:
        solver_delta_pack_lanes.set(report.delta_pack_lanes)
    if report.upload_bytes >= 0:
        solver_delta_upload_bytes.set(report.upload_bytes)
    if report.chunks_solved >= 0:
        solver_chunks_solved.set(report.chunks_solved)
        solver_chunks_skipped.set(report.chunks_skipped)


def update_kube_request_retry() -> None:
    kube_request_retries.inc()


def update_kube_request_failure() -> None:
    kube_request_failures.inc()


def update_planner_fallback() -> None:
    planner_fallback.inc()


def update_plan_schedule_len(n: int) -> None:
    plan_schedule_len.set(n)


def update_schedule_invalidated() -> None:
    schedule_invalidated.inc()


def update_taint_recovered() -> None:
    orphaned_taints_recovered.inc()


def update_degraded(degraded: bool) -> None:
    rescheduler_degraded.set(1 if degraded else 0)


def update_watch_event(resource: str) -> None:
    watch_events.labels(resource).inc()


def update_watch_relist(resource: str) -> None:
    watch_relists.labels(resource).inc()


def update_watch_stream_error(resource: str) -> None:
    watch_stream_errors.labels(resource).inc()


def update_watch_stall(resource: str) -> None:
    watch_stalls.labels(resource).inc()


def update_watch_drift(resource: str, n: int) -> None:
    watch_drift.labels(resource).inc(n)


def update_watch_presence_heal(resource: str, n: int) -> None:
    watch_presence_heals.labels(resource).inc(n)


def update_resync_audit() -> None:
    resync_audits.inc()


def update_mirror_staleness(seconds: float) -> None:
    mirror_staleness.set(seconds)


def update_freshness_bypass() -> None:
    freshness_bypass.inc()


def update_mirror_stale_planned() -> None:
    mirror_stale_planned.inc()


def update_observe_delta_events(n: int) -> None:
    observe_delta_events.set(n)


# run-scoped maxima for the service gauges (gauges only hold the last
# batch; the serve-smoke acceptance needs the run's high-water marks)
_service_batch_max = {"lanes": 0, "tenants": 0}

# high-water of concurrent resync ingests since the window reset — the
# storm bench's "never exceeded the cap" witness (the gauge alone only
# holds the instantaneous value)
_resync_ingest_max = {"inflight": 0}

# windowed queue-wait accounting: a bounded ring of recent waits per
# tenant (plus one pooled ring for the aggregate gauges). Tenant ids
# are client-supplied, so the map is bounded exactly like the server's
# tenant bookkeeping: per-ring length capped, LRU-evicted past the
# tenant cap — a churning fleet must not grow this (or /healthz, which
# serves it) without bound.
WAIT_WINDOW = 128  # recent waits kept per tenant
WAIT_TENANTS_MAX = 4096  # mirror of the server's TENANT_STATE_MAX
_tenant_waits: "OrderedDict[str, deque]" = OrderedDict()
_window_waits: deque = deque(maxlen=4096)
# requests served per tenant since the window was last reset — the
# service-share vector jain_fairness() is computed over
_tenant_served: "OrderedDict[str, int]" = OrderedDict()


def _percentile(values, q: float) -> float:
    """Nearest-rank percentile of an unsorted sequence (0 when empty) —
    the one implementation the gauges, snapshots and /healthz share."""
    if not values:
        return 0.0
    ranked = sorted(values)
    idx = min(len(ranked) - 1, max(0, int(math.ceil(q * len(ranked))) - 1))
    return float(ranked[idx])


def jain_fairness(shares) -> float:
    """Jain's fairness index (sum x)^2 / (n * sum x^2) over a vector of
    per-tenant service shares: 1.0 = perfectly even, 1/n = one tenant
    holds everything. The twin computes it over served/offered ratios
    (demand-normalized); ``service_snapshot()`` reports it over the
    windowed per-tenant served counts (meaningful under symmetric
    demand). Empty or all-zero vectors read as 1.0 — no tenants means
    nobody is being starved."""
    vals = [float(v) for v in shares]
    total = sum(vals)
    if not vals or total <= 0:
        return 1.0
    return (total * total) / (len(vals) * sum(v * v for v in vals))


def _note_tenant_wait(tenant: str, wait_ms: float) -> None:
    ring = _tenant_waits.get(tenant)
    if ring is None:
        ring = _tenant_waits[tenant] = deque(maxlen=WAIT_WINDOW)
    ring.append(wait_ms)
    _tenant_waits.move_to_end(tenant)
    _tenant_served[tenant] = _tenant_served.get(tenant, 0) + 1
    _tenant_served.move_to_end(tenant)
    while len(_tenant_waits) > WAIT_TENANTS_MAX:
        _tenant_waits.popitem(last=False)
    while len(_tenant_served) > WAIT_TENANTS_MAX:
        _tenant_served.popitem(last=False)
    _window_waits.append(wait_ms)


def update_service_request(outcome: str) -> None:
    service_requests.labels(outcome).inc()


def update_service_admission_shed(reason: str) -> None:
    """One plan request shed at an admission edge; the caller fires the
    flight 'service-shed' event with the same reason from the same site
    so the two surfaces always agree per reason."""
    service_admission_shed.labels(reason).inc()


def update_service_bucket_compile(first: bool) -> None:
    """One batched solve routed: ``first`` means its stacked shape
    family had never been solved by this process (a compile was paid);
    otherwise the jit program was shared."""
    if first:
        service_bucket_compile_misses.inc()
    else:
        service_bucket_compile_hits.inc()


def update_service_batch(
    lanes: int, tenants: int, waits, occupancy=None
) -> None:
    """One batched solve dispatched: refresh the occupancy gauges,
    observe every member request's queue wait, and feed the windowed
    per-tenant percentile rings. ``waits`` carries ``(tenant,
    wait_ms)`` pairs; ``occupancy`` is the batch's fill fraction of its
    bucket's HBM-derived cap (None when the cap is unknown)."""
    service_batch_lanes.set(int(lanes))
    service_batch_tenants.set(int(tenants))
    _service_batch_max["lanes"] = max(_service_batch_max["lanes"], int(lanes))
    _service_batch_max["tenants"] = max(
        _service_batch_max["tenants"], int(tenants)
    )
    if occupancy is not None:
        service_batch_occupancy.set(float(occupancy))
    for tenant, w in waits:
        service_queue_wait_ms.observe(float(w))
        _note_tenant_wait(str(tenant), float(w))
    service_queue_wait_p50.set(_percentile(_window_waits, 0.50))
    service_queue_wait_p99.set(_percentile(_window_waits, 0.99))


def service_tenant_wait_snapshot(top: int = 0) -> dict:
    """Windowed per-tenant queue-wait percentiles: ``{tenant: {p50_ms,
    p99_ms, n}}`` over each tenant's bounded ring of recent waits — the
    starving-tenant probe surface (/healthz), unlike the run-maxima in
    ``service_snapshot()``. ``top`` > 0 keeps only the worst ``top``
    tenants by p99 (the /healthz response stays bounded even before
    LRU eviction kicks in)."""
    out = {}
    for tenant, ring in list(_tenant_waits.items()):
        vals = list(ring)
        out[tenant] = {
            "p50_ms": round(_percentile(vals, 0.50), 3),
            "p99_ms": round(_percentile(vals, 0.99), 3),
            "n": len(vals),
        }
    if top and len(out) > top:
        worst = sorted(
            out.items(), key=lambda kv: kv[1]["p99_ms"], reverse=True
        )[:top]
        out = dict(worst)
    return out


def service_queue_wait_summary(top: int = 16) -> dict:
    """The pooled windowed percentiles plus the worst tenants' — the
    block /healthz embeds so a probe sees the fleet's CURRENT tail and
    who is in it."""
    vals = list(_window_waits)
    return {
        "p50_ms": round(_percentile(vals, 0.50), 3),
        "p99_ms": round(_percentile(vals, 0.99), 3),
        "n": len(vals),
        "tenants": service_tenant_wait_snapshot(top=top),
    }


def reset_service_window() -> None:
    """Clear the windowed wait rings and served-count shares (the fleet
    twin resets at phase boundaries so each occupancy point's
    percentiles are its own; tests reset for isolation). Cumulative
    counters and run maxima are untouched."""
    _tenant_waits.clear()
    _window_waits.clear()
    _tenant_served.clear()
    _resync_ingest_max["inflight"] = 0
    service_queue_wait_p50.set(0.0)
    service_queue_wait_p99.set(0.0)


def update_service_tenant_eviction(tenant: str) -> None:
    service_tenant_evictions.labels(tenant).inc()


def update_remote_planner_fallback() -> None:
    remote_planner_fallback.inc()


def update_remote_planner_failover() -> None:
    remote_planner_failover.inc()


def update_remote_wire_reuse() -> None:
    remote_wire_connection_reuse.inc()


def update_remote_wire_reconnect() -> None:
    remote_wire_reconnects.inc()


def update_service_device_sick(sick: bool) -> None:
    service_device_sick.set(1 if sick else 0)


def update_service_delta(outcome: str) -> None:
    """One delta request resolved: ``applied`` or ``resync`` (the
    resync site also fires the flight ``delta-resync`` event — keep
    the two surfaces firing from the same call site)."""
    service_delta_requests.labels(outcome).inc()


def update_service_wire_ingest(nbytes: int) -> None:
    service_wire_ingest_bytes.inc(max(0, int(nbytes)))


def update_service_tenant_cache(entries: int) -> None:
    service_tenant_cache.set(int(entries))


def update_service_resync_ingest(
    inflight: int, ledger_bytes: int, admitted: bool = False
) -> None:
    """Resync-ingest admission occupancy changed: refresh the
    concurrent-ingest and ledger gauges and the run high-water (the
    storm bench asserts the high-water against the configured cap).
    ``admitted`` marks the transition that admitted one more
    full-pack resync ingest."""
    if admitted:
        service_resync_ingest_admitted.inc()
    service_resync_ingest_inflight.set(int(inflight))
    service_resync_ingest_ledger.set(max(0, int(ledger_bytes)))
    _resync_ingest_max["inflight"] = max(
        _resync_ingest_max["inflight"], int(inflight)
    )


def service_snapshot() -> dict:
    """Service/agent counters via the public collect() API (tests and
    the serve-smoke harness diff before/after), plus the run's batch
    occupancy high-water marks."""
    by_outcome = {}
    for sample in service_requests.collect()[0].samples:
        if sample.name.endswith("_total"):
            by_outcome[sample.labels.get("outcome", "")] = sample.value
    lanes = tenants = 0.0
    for sample in service_batch_lanes.collect()[0].samples:
        lanes = sample.value
    for sample in service_batch_tenants.collect()[0].samples:
        tenants = sample.value
    device_sick = 0.0
    for sample in service_device_sick.collect()[0].samples:
        device_sick = sample.value
    delta_by_outcome = {}
    for sample in service_delta_requests.collect()[0].samples:
        if sample.name.endswith("_total"):
            delta_by_outcome[sample.labels.get("outcome", "")] = sample.value
    cache_entries = 0.0
    for sample in service_tenant_cache.collect()[0].samples:
        cache_entries = sample.value
    shed_by_reason = {}
    for sample in service_admission_shed.collect()[0].samples:
        if sample.name.endswith("_total"):
            shed_by_reason[sample.labels.get("reason", "")] = sample.value
    occupancy = 0.0
    for sample in service_batch_occupancy.collect()[0].samples:
        occupancy = sample.value
    resync_inflight = resync_ledger = 0.0
    for sample in service_resync_ingest_inflight.collect()[0].samples:
        resync_inflight = sample.value
    for sample in service_resync_ingest_ledger.collect()[0].samples:
        resync_ledger = sample.value
    return {
        "requests": by_outcome,
        "batch_lanes": lanes,
        "batch_tenants": tenants,
        "batch_lanes_max": _service_batch_max["lanes"],
        "batch_tenants_max": _service_batch_max["tenants"],
        "batch_occupancy": occupancy,
        "tenant_evictions": _labeled_counter_total(service_tenant_evictions),
        "remote_planner_fallback": _counter_value(remote_planner_fallback),
        "remote_planner_failover": _counter_value(remote_planner_failover),
        "wire_connection_reuse": _counter_value(remote_wire_connection_reuse),
        "wire_reconnects": _counter_value(remote_wire_reconnects),
        "device_sick": device_sick,
        "delta_requests": delta_by_outcome,
        "wire_ingest_bytes": _counter_value(service_wire_ingest_bytes),
        "tenant_cache_entries": cache_entries,
        "admission_shed": shed_by_reason,
        "resync_ingest_admitted": _counter_value(
            service_resync_ingest_admitted
        ),
        "resync_ingest_inflight": resync_inflight,
        "resync_ingest_inflight_max": _resync_ingest_max["inflight"],
        "resync_ingest_ledger_bytes": resync_ledger,
        "compile_hits": _counter_value(service_bucket_compile_hits),
        "compile_misses": _counter_value(service_bucket_compile_misses),
        "queue_wait_p50_ms": round(_percentile(_window_waits, 0.50), 3),
        "queue_wait_p99_ms": round(_percentile(_window_waits, 0.99), 3),
        "tenant_queue_wait": service_tenant_wait_snapshot(),
        "jain_served": round(jain_fairness(_tenant_served.values()), 4),
    }


def _counter_value(counter) -> float:
    for sample in counter.collect()[0].samples:
        if sample.name.endswith("_total"):
            return sample.value
    return 0.0


def robustness_snapshot() -> dict:
    """Current robustness counters via the public collect() API (tests
    diff before/after; process counters are cumulative)."""
    degraded = 0.0
    for sample in rescheduler_degraded.collect()[0].samples:
        degraded = sample.value
    return {
        "kube_request_retries": _counter_value(kube_request_retries),
        "kube_request_failures": _counter_value(kube_request_failures),
        "planner_fallback": _counter_value(planner_fallback),
        "orphaned_taints_recovered": _counter_value(orphaned_taints_recovered),
        "schedule_invalidated": _counter_value(schedule_invalidated),
        "degraded": degraded,
    }


def _labeled_counter_total(counter) -> float:
    total = 0.0
    for sample in counter.collect()[0].samples:
        if sample.name.endswith("_total"):
            total += sample.value
    return total


def freshness_snapshot() -> dict:
    """Current watch-liveness/freshness counters via the public
    collect() API (tests and the soak harness diff before/after;
    labeled counters are summed across resources)."""
    staleness = 0.0
    for sample in mirror_staleness.collect()[0].samples:
        staleness = sample.value
    delta_events = 0.0
    for sample in observe_delta_events.collect()[0].samples:
        delta_events = sample.value
    return {
        "watch_events": _labeled_counter_total(watch_events),
        "watch_relists": _labeled_counter_total(watch_relists),
        "watch_stream_errors": _labeled_counter_total(watch_stream_errors),
        "watch_stalls": _labeled_counter_total(watch_stalls),
        "watch_drift": _labeled_counter_total(watch_drift),
        "watch_presence_heals": _labeled_counter_total(watch_presence_heals),
        "resync_audits": _counter_value(resync_audits),
        "freshness_bypass": _counter_value(freshness_bypass),
        "mirror_stale_planned": _counter_value(mirror_stale_planned),
        "mirror_staleness_seconds": staleness,
        "observe_delta_events": delta_events,
    }


def update_conservatism(n_unplaceable: int, by_reason: dict) -> None:
    """Refresh the why-no-drain gauges after each solve. Every reason
    label is written every tick (absent -> 0) so a recovered cluster
    reads 0, not a stale count."""
    unplaceable_pods.set(n_unplaceable)
    for reason in BLOCKED_REASONS:
        blocked_candidates.labels(reason).set(int(by_reason.get(reason, 0)))


def conservatism_snapshot() -> dict:
    """Current gauge values via the public collect() API (test/bench
    readback — keeps prometheus_client internals out of callers)."""
    unplaceable = 0.0
    for sample in unplaceable_pods.collect()[0].samples:
        unplaceable = sample.value
    blocked = {}
    for sample in blocked_candidates.collect()[0].samples:
        blocked[sample.labels.get("reason", "")] = sample.value
    return {"unplaceable_pods": unplaceable, "blocked": blocked}


def serve(listen_address: str) -> None:
    """Start the metrics HTTP endpoint (reference rescheduler.go:126-130)."""
    host, _, port = listen_address.rpartition(":")
    start_http_server(int(port), addr=host or "localhost")
