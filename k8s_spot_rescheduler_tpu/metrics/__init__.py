"""Prometheus observability."""

from k8s_spot_rescheduler_tpu.metrics.registry import (
    observe_plan_duration,
    serve,
    update_evictions_count,
    update_node_drain_count,
    update_node_pods_count,
    update_nodes_map,
)

__all__ = [
    "observe_plan_duration",
    "serve",
    "update_evictions_count",
    "update_node_drain_count",
    "update_node_pods_count",
    "update_nodes_map",
]
