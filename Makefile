# Build/test entry points, in the spirit of the reference's Makefile
# targets (all/check/test/docker-build; reference Makefile:13-91).

IMAGE ?= k8s-spot-rescheduler-tpu
VERSION ?= $(shell python -c "import k8s_spot_rescheduler_tpu as m; print(m.VERSION)")

.PHONY: all check test bench quality replay demo dryrun docker-build clean

all: check

check: test

test:
	python -m pytest tests/ -x -q

bench:
	python bench.py

quality:
	python bench.py --quality

replay:
	python bench.py --config 5

demo:
	python -m k8s_spot_rescheduler_tpu --cluster synthetic:1 --ticks 3 -v 2 \
		--no-metrics-server --node-drain-delay 1s

dryrun:
	python __graft_entry__.py 8

docker-build:
	docker build -t $(IMAGE):v$(VERSION) .

clean:
	find . -name __pycache__ -type d -exec rm -rf {} +
