# Build/test entry points, in the spirit of the reference's Makefile
# targets (all/check/test/docker-build; reference Makefile:13-91).

IMAGE ?= k8s-spot-rescheduler-tpu
VERSION ?= $(shell python -c "import k8s_spot_rescheduler_tpu as m; print(m.VERSION)")

.PHONY: all check lint analyze audit-jaxpr verify-protocol test bench bench-smoke scale-smoke serve-smoke sched-smoke pallas-smoke chaos-smoke watch-soak fleet-chaos-smoke fleet-twin-smoke storm-smoke quality replay demo dryrun docker-build clean native

# `native` is optional (io/native_ingest.py degrades gracefully without
# the .so) — a missing C++ toolchain must not block tests, so `all`
# builds it best-effort.
all:
	-$(MAKE) native
	$(MAKE) check

# The CI entry: lint+format gate, then the project-wide analysis suite
# (ast tier), then the jaxpr-tier program audit, then the proto-tier
# protocol verification, then tests, then the smokes — mirroring the
# reference's fmt/golangci-lint/vet/test chain (reference
# Makefile:36-65). tools/lint.py is the fmt+golangci-lint stand-in and
# tools/analysis is the go-vet analog, three tiers deep (this image
# ships no Python linter and installs are forbidden).
check: lint analyze audit-jaxpr verify-protocol test bench-smoke scale-smoke serve-smoke sched-smoke pallas-smoke repair-smoke chaos-smoke watch-soak fleet-chaos-smoke fleet-twin-smoke storm-smoke

lint:
	python tools/lint.py

# Project-wide static analysis, ast tier (docs/ANALYSIS.md): JAX
# hot-path vets (host-sync, donation, recompile triggers), cross-module
# contracts (metrics / config+CLI+docs / kube write-retry /
# jit-root<->HOT_PROGRAMS manifest lockstep), lock discipline.
# The watchdog keeps `make check` fast: the run must finish in 10 s.
analyze:
	python -m tools.analysis --tier ast --max-seconds 10

# Jaxpr-tier program audit (docs/ANALYSIS.md "Jaxpr tier"): every
# HOT_PROGRAMS entry traced shape-only on CPU and vetted for dtype
# promotions, index widths at the declared 1M-pod/100k-node max shapes,
# host transfers / donation aliasing, and HBM-estimator reconciliation.
# Pure abstract eval — no device, no execution; must finish in 30 s.
audit-jaxpr:
	env JAX_PLATFORMS=cpu python -m tools.analysis --tier jaxpr --max-seconds 30

# Proto-tier protocol verification (docs/ANALYSIS.md "Protocol tier"):
# exhaustively explores the wire/resync/breaker/admission protocol
# model (service/protocol_model.py) — 2 agents x 2 replicas under
# message loss, reordering, duplication and a replica restart — proving
# the safety invariants (single full-pack per restart epoch, no delta
# over a mismatched fingerprint, admission inflight <= cap, version-mix
# frame legality) and storm-drain liveness on every reachable state,
# then binds the model's tables to the live wire/agent/server constants
# in both directions (protocol-contract) so neither side can drift
# silently. Pure Python BFS — no device, no network; must finish in 60 s.
verify-protocol:
	python -m tools.analysis --tier proto --max-seconds 60

# best-effort native build first: the native differential suite fails
# (not skips) when a toolchain exists but the library won't load
test:
	-$(MAKE) native
	python -m pytest tests/ -x -q

# Native ingest engine (C++17, no dependencies): apiserver JSON -> columnar
# batches. Optional — io/native_ingest.py falls back to pure Python when
# the shared library is absent.
native: k8s_spot_rescheduler_tpu/native/_ingest.so

k8s_spot_rescheduler_tpu/native/_ingest.so: k8s_spot_rescheduler_tpu/native/ingest.cc
	g++ -std=c++17 -O2 -fPIC -shared -o $@ $<

bench:
	python bench.py

# Tiny CPU-only proof of the device-resident incremental tick path:
# 5 ticks at C=S=64; fails unless the steady-state delta tick uploads
# fewer bytes than the first full-pack tick.
bench-smoke:
	env JAX_PLATFORMS=cpu python bench.py --smoke --watchdog 600

# Shape-only 20x proof (CPU, ~1 s): the dispatch ladder at the
# 1M-pod/100k-node shapes must keep repair LIVE on the carry-streamed
# narrow tier under the v5e per-device budget (honest estimator
# breakdown asserted), and the streamed union must trace at the
# per-device lane-block shapes — no device solve.
scale-smoke:
	env JAX_PLATFORMS=cpu python bench.py --scale-smoke --watchdog 300

# Multi-tenant planner-service smoke (CPU-only): >=4 synthetic tenant
# agents plan concurrently through one in-process service over real
# HTTP; fails unless every tenant's selection is bit-identical to its
# solo in-process SolverPlanner plan, at least one batched solve
# carried lanes from >=2 tenants (service_batch_lanes), and no agent
# fell back to the local oracle.
serve-smoke:
	env JAX_PLATFORMS=cpu python bench.py --serve-smoke --watchdog 600

# Drain-schedule smoke (CPU-only, numpy-oracle parity path, FakeClock,
# <60 s): schedule-mode exhaustion must free the same nodes as per-tick
# planning in <= ceil(drains/horizon)+2 planner fetches; injected churn
# must invalidate (flight delta == metric delta) and re-plan, never
# mis-evict; the wire schedule (KIND_PLAN_SCHEDULE) must be
# bit-identical to the local device cut; a replica killed under a
# schedule in flight must cost nothing until the next cut fails over.
sched-smoke:
	env JAX_PLATFORMS=cpu python bench.py --sched-smoke --watchdog 300

# Pallas stream-kernel parity smoke (CPU interpret mode, <30 s): the
# fused elect-then-commit best-fit kernel vs the XLA carry-streamed
# step vs the host oracle, bit-identical selections across >=3 chunk
# counts on 3 permuted packs.
pallas-smoke:
	env JAX_PLATFORMS=cpu python bench.py --pallas-smoke --watchdog 30

# 8-virtual-device spot-chunked repair smoke: a drain only repair can
# prove, at a budget that previously forced the repair-less 2-D tier —
# must dispatch to the cand tier with chunked repair, bit-identical to
# plan_repair_oracle, solver_repair_chunks > 1, repair_unavailable 0
# (and still 1 past the new fully-chunked ceiling).
repair-smoke:
	env JAX_PLATFORMS=cpu \
		XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python __graft_entry__.py 8 chunked-repair-only

# Seeded chaos soak of the control plane (CPU-only, seconds of wall):
# 300 ticks under the heavy fault profile + scripted 429s + one
# mid-drain interrupt; fails unless the loop never crashes, no orphaned
# ToBeDeleted taint survives, and drains resume once faults clear.
chaos-smoke:
	env JAX_PLATFORMS=cpu python bench.py --chaos --chaos-ticks 300 --watchdog 300

# Seeded freshness soak of the watch observe plane (CPU-only, ~1 s of
# wall on a virtual clock): 300 ticks with open-but-silent stalls,
# stream drops, scripted 410s and one injected mirror corruption; fails
# unless stalls are detected, drift heals within one resync interval,
# zero ticks plan from an over-budget mirror, every full LIST is
# accounted to a relist or audit, and the mirror packs bit-identically
# to a fresh LIST at end-state.
watch-soak:
	env JAX_PLATFORMS=cpu python bench.py --watch-soak --watch-soak-ticks 300 --watchdog 300

# Fleet failure-domain smoke (CPU-only, seconds of wall on a virtual
# clock): 4 agents x 2 planner-service replicas over real HTTP under
# seeded wire/HTTP faults, one scripted sick-device phase and one
# graceful replica kill + warm restart; fails unless zero agent crashes,
# every selection is bit-identical to the solo in-process plan,
# sick-detection/recovery and failover edges fire, flight-recorder
# deltas equal metric deltas, and the restarted replica pre-warms from
# its persisted state. Budget: <60 s wall.
fleet-chaos-smoke:
	env JAX_PLATFORMS=cpu python bench.py --fleet-chaos --watchdog 60

# Fleet-twin smoke (CPU-only, seconds of wall on a virtual clock): 64
# heterogeneous tenant twins x 2 real-HTTP planner-service replicas
# through ~20 simulated minutes — 4 occupancy phases with correlated
# spot-interruption storms, one replica kill + warm restart per phase,
# and tenant join/leave churn — plus the deterministic induction that
# drives every labeled service_admission_shed_total reason through a
# live replica. Fails unless zero twin crashes, every spot-checked
# selection is bit-identical to the solo in-process plan, the capacity
# curve is monotone and non-degenerate, and flight-recorder deltas
# equal metric deltas for failover and every shed reason. Budget: <60 s.
fleet-twin-smoke:
	env JAX_PLATFORMS=cpu python bench.py --fleet-twin-smoke --watchdog 60

# Resync-storm survival (FakeClock, >=32 twins x 2 replicas): one
# replica killed + warm-restarted under full load, wiping its tenant
# cache — the full-pack resync herd must be SHED by the bounded ingest
# admission class, never collapse the delta traffic. Fails unless
# concurrent ingests stay under the cap, no tenant resyncs twice,
# unaffected tenants hold the queue-wait SLO, the fleet converges in
# O(affected) full packs, and every shed/resync ledger (labeled metric
# vs flight events vs twin counters) agrees exactly. Budget: <60 s.
storm-smoke:
	env JAX_PLATFORMS=cpu python bench.py --storm-smoke --watchdog 60

quality:
	python bench.py --quality

replay:
	python bench.py --config 5

demo:
	python -m k8s_spot_rescheduler_tpu --cluster synthetic:1 --ticks 3 -v 2 \
		--no-metrics-server --node-drain-delay 1s

dryrun:
	python __graft_entry__.py 8

docker-build:
	docker build -t $(IMAGE):v$(VERSION) .

clean:
	find . -name __pycache__ -type d -exec rm -rf {} +
